"""Pallas flash-attention kernel tests (interpret mode on the CPU mesh;
numerics checked against dense attention)."""

import jax
import jax.numpy as jnp
from comfyui_distributed_tpu.utils.jax_compat import shard_map
import numpy as np
import pytest

from comfyui_distributed_tpu.ops.flash_attention import flash_attention

pytestmark = pytest.mark.slow  # compile-heavy: builds/jits real model stacks


def dense_reference(q, k, v):
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))


def rand_qkv(key, B=1, Nq=128, Nk=128, H=2, D=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, Nq, H, D), dtype)
    k = jax.random.normal(kk, (B, Nk, H, D), dtype)
    v = jax.random.normal(kv, (B, Nk, H, D), dtype)
    return q, k, v


class TestNumerics:
    def test_block_aligned(self):
        q, k, v = rand_qkv(jax.random.key(0), Nq=256, Nk=256)
        out = flash_attention(q, k, v, interpret=True)
        ref = dense_reference(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_ragged_lengths_masked(self):
        """Nq/Nk not multiples of the block sizes → padding is masked out."""
        q, k, v = rand_qkv(jax.random.key(1), Nq=100, Nk=77)
        out = flash_attention(q, k, v, interpret=True)
        ref = dense_reference(q, k, v)
        assert out.shape == (1, 100, 2, 64)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_multi_kv_blocks_accumulate(self):
        """Nk spanning several K blocks exercises the streaming-softmax
        carry (running max / denominator / accumulator rescale)."""
        q, k, v = rand_qkv(jax.random.key(2), Nq=128, Nk=512)
        out = flash_attention(q, k, v, block_k=128, interpret=True)
        ref = dense_reference(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_bf16_inputs(self):
        q, k, v = rand_qkv(jax.random.key(3), Nq=128, Nk=256,
                           dtype=jnp.bfloat16)
        out = flash_attention(q, k, v, interpret=True)
        ref = dense_reference(q, k, v)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(out.astype(np.float32), ref,
                                   atol=2e-2, rtol=2e-2)

    def test_extreme_logits_stable(self):
        """Large-magnitude logits must not overflow exp (running-max
        subtraction)."""
        q, k, v = rand_qkv(jax.random.key(4), Nq=128, Nk=256)
        q = q * 30.0
        out = flash_attention(q, k, v, interpret=True)
        ref = dense_reference(q, k, v)
        assert np.isfinite(np.asarray(out)).all()
        np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)

    def test_batch_and_heads(self):
        q, k, v = rand_qkv(jax.random.key(5), B=2, Nq=64, Nk=64, H=4, D=32)
        out = flash_attention(q, k, v, interpret=True)
        ref = dense_reference(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_cross_attention_shape(self):
        """Cross attention: 77-token text context vs image queries."""
        q, k, v = rand_qkv(jax.random.key(6), Nq=256, Nk=77)
        out = flash_attention(q, k, v, interpret=True)
        ref = dense_reference(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


class TestShardMap:
    def test_inside_shard_map_dp(self):
        """The production path: attention running inside the dp-sharded
        generation program (vma must propagate to the pallas out_shape)."""
        from jax.sharding import PartitionSpec as P

        from comfyui_distributed_tpu.parallel.mesh import build_mesh

        mesh = build_mesh({"dp": 8})
        q, k, v = rand_qkv(jax.random.key(8), B=8, Nq=64, Nk=64, H=2, D=32)

        def per_shard(q, k, v):
            return flash_attention(q, k, v, interpret=True)

        f = jax.jit(shard_map(
            per_shard, mesh=mesh,
            in_specs=(P("dp"), P("dp"), P("dp")),
            out_specs=P("dp")))
        out = f(q, k, v)
        ref = dense_reference(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


class TestDispatch:
    def test_full_attention_env_toggle(self, monkeypatch):
        from comfyui_distributed_tpu.ops import attention as attn

        monkeypatch.setenv("CDT_FLASH_ATTENTION", "0")
        assert not attn._flash_enabled()
        monkeypatch.setenv("CDT_FLASH_ATTENTION", "1")
        assert attn._flash_enabled()

    def test_seq_length_gate(self, monkeypatch):
        """r04: with no explicit env the flash default is gated on q
        length — below CDT_FLASH_MIN_SEQ the XLA fused lowering wins on
        TPU (measured: scripts/mfu_probe.py, SDXL 1024² flash 0.1763
        s/fwd vs XLA 0.1677), so short sequences must resolve to False
        even on TPU. Off-TPU (this CPU host) both resolve False; the
        explicit flags override everything."""
        from comfyui_distributed_tpu.ops import attention as attn

        monkeypatch.delenv("CDT_FLASH_ATTENTION", raising=False)
        assert attn._flash_min_seq() == 8192
        monkeypatch.setenv("CDT_FLASH_MIN_SEQ", "4096")
        assert attn._flash_min_seq() == 4096
        # short q: gated off regardless of platform
        assert not attn._flash_enabled(q_len=4095)
        # explicit force wins over the gate
        monkeypatch.setenv("CDT_FLASH_ATTENTION", "1")
        assert attn._flash_enabled(q_len=64)
        monkeypatch.setenv("CDT_FLASH_ATTENTION", "0")
        assert not attn._flash_enabled(q_len=1 << 20)

    def test_prefer_flash_safe_off_tpu(self, monkeypatch):
        """prefer_flash skips the seq-length gate but NOT the platform
        check: on this CPU host it must fall through to the XLA path
        (a pallas call would need interpret mode) and still be exact.
        The offload executor relies on this — its block programs set
        prefer_flash unconditionally (OOM-measured necessity on TPU)."""
        from comfyui_distributed_tpu.ops import attention as attn

        monkeypatch.delenv("CDT_FLASH_ATTENTION", raising=False)
        q, k, v = rand_qkv(jax.random.key(11), Nq=32, Nk=32)
        out = attn.full_attention(q, k, v, prefer_flash=True)
        np.testing.assert_allclose(out, dense_reference(q, k, v),
                                   atol=2e-5, rtol=2e-5)

    def test_full_attention_uses_flash_when_forced(self, monkeypatch):
        from comfyui_distributed_tpu.ops import attention as attn

        monkeypatch.setenv("CDT_FLASH_ATTENTION", "1")
        q, k, v = rand_qkv(jax.random.key(7), Nq=64, Nk=64)
        out = attn.full_attention(q, k, v)
        ref = dense_reference(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


class TestLayoutVariants:
    """The packed-heads ([B,N,H·D]-native) pallas call and the classic
    pre-transposed [B·H,N,D] (bh) call are the same math — packed keeps
    q/k/v in the QKV projection's own layout and splits heads inside the
    kernel (r04 boundary-relayout fix, docs/roofline.md finding 1)."""

    @pytest.mark.parametrize("shape", [
        (2, 300, 4, 64, 300),     # padded tails on both q and k
        (1, 1024, 10, 64, 77),    # SDXL cross-attention geometry
        (2, 513, 3, 128, 200),    # D=128, odd lengths
        (1, 600, 24, 128, 500),   # FLUX geometry: H*D=3072 exceeds
                                  # _PACKED_MAX_HD -> classic call (the
                                  # packed request must fall back, not
                                  # crash; measured slower at r04)
    ])
    def test_packed_matches_bh(self, monkeypatch, shape):
        from comfyui_distributed_tpu.ops.flash_attention import flash_attention

        b, nq, h, d, nk = shape
        q = jax.random.normal(jax.random.key(0), (b, nq, h, d))
        k = jax.random.normal(jax.random.key(1), (b, nk, h, d))
        v = jax.random.normal(jax.random.key(2), (b, nk, h, d))
        monkeypatch.delenv("CDT_FLASH_LAYOUT", raising=False)
        a = flash_attention(q, k, v, interpret=True, layout="packed")
        b_ = flash_attention(q, k, v, interpret=True, layout="bh")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-6, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(a), dense_reference(q, k, v),
                                   atol=5e-2, rtol=5e-2)


class TestShapeGate:
    """r04 final gate: on TPU (simulated here by patching jax.devices)
    the default picks flash per shape — packed-legal layouts engage at
    q ≥ 1024 with K ≥ 256 (measured crossover, docs/roofline.md finding
    1a), packed-illegal layouts keep the classic 8192 gate."""

    @pytest.fixture()
    def on_tpu(self, monkeypatch):
        import types

        from comfyui_distributed_tpu.ops import attention as attn

        monkeypatch.delenv("CDT_FLASH_ATTENTION", raising=False)
        monkeypatch.delenv("CDT_FLASH_MIN_SEQ", raising=False)
        monkeypatch.delenv("CDT_FLASH_MIN_SEQ_PACKED", raising=False)
        monkeypatch.delenv("CDT_FLASH_MIN_KV_PACKED", raising=False)
        monkeypatch.delenv("CDT_FLASH_LAYOUT", raising=False)
        monkeypatch.delenv("CDT_FLASH_BLOCK_Q", raising=False)
        monkeypatch.delenv("CDT_FLASH_BLOCK_K", raising=False)
        fake = types.SimpleNamespace(platform="tpu")
        monkeypatch.setattr(attn.jax, "devices", lambda *a: [fake])
        return attn

    def test_packed_legal_engages_at_sdxl_lengths(self, on_tpu):
        # SDXL self-attention: 4096 tokens, 10 heads × 64
        assert on_tpu._flash_enabled(q_len=4096, kv_len=4096,
                                     num_heads=10, head_dim=64)
        # the 32² block: 1024 tokens — exactly at the packed floor
        assert on_tpu._flash_enabled(q_len=1024, kv_len=1024,
                                     num_heads=20, head_dim=64)
        assert not on_tpu._flash_enabled(q_len=512, kv_len=512,
                                         num_heads=20, head_dim=64)

    def test_short_kv_cross_attention_stays_on_xla(self, on_tpu):
        # SDXL cross-attention: K = 77 text tokens → one mostly-padding
        # K block, measured behind XLA
        assert not on_tpu._flash_enabled(q_len=4096, kv_len=77,
                                         num_heads=10, head_dim=64)

    def test_packed_illegal_keeps_classic_gate(self, on_tpu):
        # FLUX: H·D = 3072 > _PACKED_MAX_HD → classic call, 8192 gate
        assert not on_tpu._flash_enabled(q_len=4608, kv_len=4608,
                                         num_heads=24, head_dim=128)
        assert on_tpu._flash_enabled(q_len=9000, kv_len=9000,
                                     num_heads=24, head_dim=128)

    def test_shape_free_call_keeps_classic_gate(self, on_tpu):
        # callers that pass only q_len (no head geometry) get the
        # classic 8192 threshold
        assert not on_tpu._flash_enabled(q_len=4096)
        assert on_tpu._flash_enabled(q_len=8192)

    def test_short_kv_long_q_falls_through_to_classic_gate(self, on_tpu):
        # packed-legal geometry whose KV floor fails must still reach
        # the classic bh gate at very long q (streamed-softmax memory
        # win), not silently drop flash entirely (r04 advisor finding)
        assert on_tpu._flash_enabled(q_len=16384, kv_len=77,
                                     num_heads=10, head_dim=64)
        assert not on_tpu._flash_enabled(q_len=4096, kv_len=77,
                                         num_heads=10, head_dim=64)

    def test_packed_layout_requires_lane_aligned_head_dim(self, monkeypatch):
        # H=128, D=16 passes the packed-width checks but would unroll a
        # 128-way head loop over 16-wide lane slices — excluded
        from comfyui_distributed_tpu.ops.flash_attention import _layout_packed

        monkeypatch.delenv("CDT_FLASH_LAYOUT", raising=False)
        assert not _layout_packed(128, 16)
        assert _layout_packed(10, 64)
        assert _layout_packed(16, 128)

    def test_malformed_gate_env_falls_back(self, on_tpu, monkeypatch):
        # an env typo must degrade to the default, not crash the gate
        monkeypatch.setenv("CDT_FLASH_MIN_SEQ_PACKED", "banana")
        assert on_tpu._flash_enabled(q_len=4096, kv_len=4096,
                                     num_heads=10, head_dim=64)

    def test_block_env_knobs_reach_kernel(self, monkeypatch):
        """CDT_FLASH_BLOCK_Q/K (r05 tuning knobs) change the kernel's
        block geometry without changing its math; non-positive values
        fall back to the defaults instead of crashing the grid math."""
        q, k, v = rand_qkv(jax.random.key(12), Nq=256, Nk=512)
        ref = dense_reference(q, k, v)
        monkeypatch.setenv("CDT_FLASH_BLOCK_Q", "128")
        monkeypatch.setenv("CDT_FLASH_BLOCK_K", "128")
        out = flash_attention(q, k, v, interpret=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
        monkeypatch.setenv("CDT_FLASH_BLOCK_Q", "0")
        monkeypatch.setenv("CDT_FLASH_BLOCK_K", "-64")
        out = flash_attention(q, k, v, interpret=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
