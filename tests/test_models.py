"""Model zoo shape/numerics tests (tiny configs, CPU)."""

import jax
import jax.numpy as jnp
import numpy as np

from comfyui_distributed_tpu.models.text import TextEncoder, TextEncoderConfig
from comfyui_distributed_tpu.models.unet import UNetConfig, init_unet
from comfyui_distributed_tpu.models.vae import AutoencoderKL, VAEConfig


def test_unet_tiny_forward():
    cfg = UNetConfig.tiny()
    model, params = init_unet(cfg, jax.random.key(0), sample_shape=(8, 8, 4), context_len=16)
    x = jnp.ones((2, 8, 8, 4))
    t = jnp.array([0.0, 500.0])
    ctx = jnp.ones((2, 16, cfg.context_dim))
    y = jnp.ones((2, cfg.adm_in_channels))
    out = model.apply(params, x, t, ctx, y)
    assert out.shape == (2, 8, 8, 4)
    assert out.dtype == jnp.float32
    assert np.isfinite(np.asarray(out)).all()


def test_unet_sdxl_config_shape():
    cfg = UNetConfig.sdxl()
    assert cfg.model_channels == 320
    assert cfg.transformer_depth == (0, 2, 10)
    assert cfg.context_dim == 2048
    assert cfg.heads_for(640) == 10  # 640 / 64


def test_vae_tiny_roundtrip_shapes():
    cfg = VAEConfig.tiny()
    vae = AutoencoderKL(cfg).init(jax.random.key(0), image_hw=(16, 16))
    img = jnp.zeros((2, 16, 16, 3))
    lat = vae.encode(img)
    assert lat.shape == (2, 8, 8, cfg.latent_channels)
    dec = vae.decode(lat)
    assert dec.shape == (2, 16, 16, 3)
    assert np.isfinite(np.asarray(dec)).all()


def test_text_encoder_tiny():
    cfg = TextEncoderConfig.tiny()
    enc = TextEncoder(cfg).init(jax.random.key(0))
    ctx, pooled = enc.encode(["a photo of a cat", "a dog"])
    assert ctx.shape == (2, cfg.max_len, cfg.output_dim)
    assert pooled.shape == (2, cfg.pooled_dim)
    # deterministic tokenization
    ctx2, _ = enc.encode(["a photo of a cat", "a dog"])
    np.testing.assert_array_equal(np.asarray(ctx), np.asarray(ctx2))
    # different prompts → different conditioning
    ctx3, _ = enc.encode(["something else entirely", "a dog"])
    assert not np.allclose(np.asarray(ctx[0]), np.asarray(ctx3[0]))
