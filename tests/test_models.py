"""Model zoo shape/numerics tests (tiny configs, CPU)."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from comfyui_distributed_tpu.models.text import TextEncoder, TextEncoderConfig
from comfyui_distributed_tpu.models.unet import UNetConfig, init_unet
from comfyui_distributed_tpu.models.vae import AutoencoderKL, VAEConfig

pytestmark = pytest.mark.slow  # compile-heavy: builds/jits real model stacks


def test_unet_tiny_forward():
    cfg = UNetConfig.tiny()
    model, params = init_unet(cfg, jax.random.key(0), sample_shape=(8, 8, 4), context_len=16)
    x = jnp.ones((2, 8, 8, 4))
    t = jnp.array([0.0, 500.0])
    ctx = jnp.ones((2, 16, cfg.context_dim))
    y = jnp.ones((2, cfg.adm_in_channels))
    out = model.apply(params, x, t, ctx, y)
    assert out.shape == (2, 8, 8, 4)
    assert out.dtype == jnp.float32
    assert np.isfinite(np.asarray(out)).all()


def test_unet_sdxl_config_shape():
    cfg = UNetConfig.sdxl()
    assert cfg.model_channels == 320
    assert cfg.transformer_depth == (0, 2, 10)
    assert cfg.context_dim == 2048
    assert cfg.heads_for(640) == 10  # 640 / 64


def test_vae_tiny_roundtrip_shapes():
    cfg = VAEConfig.tiny()
    vae = AutoencoderKL(cfg).init(jax.random.key(0), image_hw=(16, 16))
    img = jnp.zeros((2, 16, 16, 3))
    lat = vae.encode(img)
    assert lat.shape == (2, 8, 8, cfg.latent_channels)
    dec = vae.decode(lat)
    assert dec.shape == (2, 16, 16, 3)
    assert np.isfinite(np.asarray(dec)).all()


def test_text_encoder_tiny():
    cfg = TextEncoderConfig.tiny()
    enc = TextEncoder(cfg).init(jax.random.key(0))
    ctx, pooled = enc.encode(["a photo of a cat", "a dog"])
    assert ctx.shape == (2, cfg.max_len, cfg.output_dim)
    assert pooled.shape == (2, cfg.pooled_dim)
    # deterministic tokenization
    ctx2, _ = enc.encode(["a photo of a cat", "a dog"])
    np.testing.assert_array_equal(np.asarray(ctx), np.asarray(ctx2))
    # different prompts → different conditioning
    ctx3, _ = enc.encode(["something else entirely", "a dog"])
    assert not np.allclose(np.asarray(ctx[0]), np.asarray(ctx3[0]))


def test_unet_remat_matches_plain():
    """remat=True recomputes activations but must be numerically identical."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from comfyui_distributed_tpu.models.unet import UNetConfig, init_unet

    cfg = UNetConfig.tiny(dtype="float32")
    model, params = init_unet(cfg, jax.random.key(0), sample_shape=(8, 8, 4),
                              context_len=8)
    cfg_r = dataclasses.replace(cfg, remat=True)
    from comfyui_distributed_tpu.models.unet import UNet2D

    model_r = UNet2D(cfg_r)
    x = jax.random.normal(jax.random.key(1), (1, 8, 8, 4))
    t = jnp.ones((1,)) * 0.3
    ctx = jax.random.normal(jax.random.key(2), (1, 8, cfg.context_dim))
    y = jnp.ones((1, cfg.adm_in_channels))
    a = np.asarray(model.apply(params, x, t, ctx, y))
    b = np.asarray(model_r.apply(params, x, t, ctx, y))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)


def test_dit_remat_matches_plain():
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from comfyui_distributed_tpu.models.dit import DiT, DiTConfig, init_dit

    cfg = dataclasses.replace(DiTConfig.tiny(pos_embed="rope"), dtype="float32")
    model, params = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                             context_len=6)
    model_r = DiT(dataclasses.replace(cfg, remat=True))
    x = jax.random.normal(jax.random.key(1), (1, 8, 8, 4))
    args = (x, jnp.ones((1,)) * 0.4,
            jax.random.normal(jax.random.key(2), (1, 6, 32)),
            jnp.ones((1, 16)))
    a = np.asarray(model.apply(params, *args))
    b = np.asarray(model_r.apply(params, *args))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)
