"""Video container I/O: round-trips for every supported format, the
pure-Python AVI MJPG+PCM muxer/demuxer, frame-selection knobs, and the
LoadVideo/SaveVideo graph nodes (reference-ecosystem parity surface:
VHS_LoadVideo / VHS_VideoCombine in
``/root/reference/workflows/distributed-upscale-video.json`` — the
reference free-rides on VideoHelperSuite + ffmpeg; here the edge is
owned, ffmpeg-free)."""

import numpy as np
import pytest

from comfyui_distributed_tpu.utils.exceptions import ValidationError
from comfyui_distributed_tpu.utils.video_io import (
    load_video,
    read_avi_mjpg,
    save_video,
    write_avi_mjpg,
)


def smooth_frames(t=6, h=32, w=48):
    """Gradient frames (JPEG-friendly, unlike noise) with per-frame
    brightness so frame ORDER is verifiable after a round trip."""
    y = np.linspace(0.0, 0.6, h, dtype=np.float32)[:, None, None]
    x = np.linspace(0.0, 0.3, w, dtype=np.float32)[None, :, None]
    base = np.broadcast_to(y + x, (h, w, 3))
    return np.stack([np.clip(base + 0.05 * i, 0, 1) for i in range(t)])


def sine_audio(seconds=0.75, sr=16000, hz=440.0):
    t = np.arange(int(seconds * sr), dtype=np.float32) / sr
    wf = (0.5 * np.sin(2 * np.pi * hz * t)).astype(np.float32)
    return {"waveform": wf[None, None, :], "sample_rate": sr}


class TestAviMuxer:
    def test_round_trip_video_only(self, tmp_path):
        frames = smooth_frames()
        p = tmp_path / "clip.avi"
        write_avi_mjpg(p, (frames * 255 + 0.5).astype(np.uint8), fps=8.0)
        out = read_avi_mjpg(p)
        assert out is not None
        assert out["frames"].shape == frames.shape
        assert out["fps"] == 8.0
        assert out["audio"] is None
        np.testing.assert_allclose(out["frames"], frames, atol=0.06)

    def test_round_trip_with_muxed_audio(self, tmp_path):
        frames = smooth_frames()
        audio = sine_audio()
        pcm = (np.clip(audio["waveform"][0], -1, 1) * 32767).astype(
            np.int16).T.copy()
        p = tmp_path / "clip.avi"
        write_avi_mjpg(p, (frames * 255 + 0.5).astype(np.uint8), fps=8.0,
                       pcm=pcm, sample_rate=audio["sample_rate"])
        out = read_avi_mjpg(p)
        assert out["audio"] is not None
        assert out["audio"]["sample_rate"] == audio["sample_rate"]
        got = out["audio"]["waveform"]
        assert got.shape == audio["waveform"].shape   # full track survives
        np.testing.assert_allclose(got, audio["waveform"], atol=1e-3)

    def test_riff_structure(self, tmp_path):
        """The container advertises itself correctly: RIFF/AVI magic,
        MJPG fourcc, an idx1 index — what external players key on."""
        p = tmp_path / "clip.avi"
        write_avi_mjpg(p, (smooth_frames() * 255).astype(np.uint8), fps=8.0)
        buf = p.read_bytes()
        assert buf[:4] == b"RIFF" and buf[8:12] == b"AVI "
        assert b"MJPG" in buf and b"idx1" in buf and b"movi" in buf
        # RIFF size field spans the file
        import struct

        assert struct.unpack("<I", buf[4:8])[0] == len(buf) - 8

    def test_non_avi_returns_none(self, tmp_path):
        p = tmp_path / "not.avi"
        p.write_bytes(b"garbage that is not RIFF")
        assert read_avi_mjpg(p) is None

    def test_stereo_audio(self, tmp_path):
        frames = (smooth_frames(t=4) * 255).astype(np.uint8)
        sr = 8000
        t = np.arange(4000, dtype=np.float32) / sr
        stereo = np.stack([np.sin(2 * np.pi * 220 * t),
                           np.sin(2 * np.pi * 330 * t)]) * 0.4
        pcm = (stereo.T * 32767).astype(np.int16).copy()
        p = tmp_path / "stereo.avi"
        write_avi_mjpg(p, frames, fps=4.0, pcm=pcm, sample_rate=sr)
        out = read_avi_mjpg(p)
        assert out["audio"]["waveform"].shape == (1, 2, 4000)
        np.testing.assert_allclose(out["audio"]["waveform"][0],
                                   stereo.astype(np.float32), atol=1e-3)


class TestSaveLoadVideo:
    @pytest.mark.parametrize("ext", ["mp4", "webm", "avi"])
    def test_round_trip(self, tmp_path, ext):
        frames = smooth_frames()
        p = tmp_path / f"clip.{ext}"
        written = save_video(p, frames, fps=8.0)
        assert written == [str(p)]
        out = load_video(p)
        assert out["frames"].shape == frames.shape
        assert out["frame_count"] == frames.shape[0]
        # lossy codecs: loose tolerance, but order must survive
        means = out["frames"].mean(axis=(1, 2, 3))
        assert (np.diff(means) > 0).all()

    def test_cv2_formats_carry_audio_as_sidecar(self, tmp_path):
        p = tmp_path / "clip.mp4"
        audio = sine_audio()
        written = save_video(p, smooth_frames(), fps=8.0, audio=audio)
        assert written == [str(p), str(p.with_suffix(".wav"))]
        out = load_video(p)
        assert out["audio"] is not None
        assert out["audio"]["sample_rate"] == audio["sample_rate"]
        np.testing.assert_allclose(out["audio"]["waveform"],
                                   audio["waveform"], atol=1e-3)

    def test_avi_muxes_audio_no_sidecar(self, tmp_path):
        p = tmp_path / "clip.avi"
        written = save_video(p, smooth_frames(), fps=8.0, audio=sine_audio())
        assert written == [str(p)]
        assert not p.with_suffix(".wav").exists()
        assert load_video(p)["audio"] is not None

    def test_frame_selection(self, tmp_path):
        p = tmp_path / "clip.avi"
        save_video(p, smooth_frames(t=10), fps=8.0)
        out = load_video(p, skip_first_frames=2, select_every_nth=2,
                         frame_load_cap=3)
        assert out["frames"].shape[0] == 3
        full = load_video(p)["frames"]
        np.testing.assert_allclose(out["frames"], full[2::2][:3])

    def test_selection_keeps_fps_and_audio_coherent(self, tmp_path):
        """Stride divides the fps and the audio is trimmed to the span
        the selected frames cover — the saved result plays at the same
        wall-clock speed as the source (VHS_LoadVideo behavior)."""
        p = tmp_path / "clip.avi"
        audio = sine_audio(seconds=10 / 8.0)         # exactly 10 frames @ 8fps
        save_video(p, smooth_frames(t=10), fps=8.0, audio=audio)
        out = load_video(p, select_every_nth=2)
        assert out["frames"].shape[0] == 5
        assert out["fps"] == 4.0                     # 8 / stride 2
        sr = audio["sample_rate"]
        # span covered: frames 0..8 inclusive → 9/8 s of audio
        assert out["audio"]["waveform"].shape[-1] == round(9 / 8.0 * sr)
        skip = load_video(p, skip_first_frames=4)
        # skipped prefix removed from the track
        np.testing.assert_allclose(
            skip["audio"]["waveform"][0, 0, :100],
            audio["waveform"][0, 0, round(4 / 8.0 * sr):][:100], atol=1e-3)
        assert skip["fps"] == 8.0                    # no stride → fps kept

    def test_cap_stops_decode_early(self, tmp_path):
        """frame_load_cap bounds decode work on the cv2 path (no
        full-container materialization) — frames beyond the cap are
        never stored."""
        p = tmp_path / "long.mp4"
        save_video(p, smooth_frames(t=40), fps=8.0)
        out = load_video(p, frame_load_cap=4)
        assert out["frames"].shape[0] == 4
        assert out["frame_count"] == 4

    def test_validation_errors(self, tmp_path):
        with pytest.raises(ValidationError):
            load_video(tmp_path / "missing.mp4")
        with pytest.raises(ValidationError):
            save_video(tmp_path / "x.gif", smooth_frames())
        with pytest.raises(ValidationError):
            save_video(tmp_path / "x.mp4", np.zeros((0, 8, 8, 3)))
        save_video(tmp_path / "ok.avi", smooth_frames(t=4), fps=4.0)
        with pytest.raises(ValidationError):
            load_video(tmp_path / "ok.avi", skip_first_frames=99)


class TestVideoNodes:
    def _ctx(self, tmp_path):
        return {"input_dir": str(tmp_path), "output_dir": str(tmp_path)}

    def test_save_then_load_nodes(self, tmp_path):
        from comfyui_distributed_tpu.graph.executor import GraphExecutor

        save_video(tmp_path / "in.avi", smooth_frames(), fps=8.0,
                   audio=sine_audio())
        prompt = {
            "1": {"class_type": "LoadVideo", "inputs": {"video": "in.avi"}},
            "2": {"class_type": "SaveVideo", "inputs": {
                "images": ["1", 0], "audio": ["1", 1],
                "frame_rate": ["1", 2], "format": "avi",
                "filename_prefix": "out"}},
        }
        outputs = GraphExecutor(self._ctx(tmp_path)).execute(prompt)
        frames, audio, fps, count = outputs["1"]
        assert np.asarray(frames).shape == (6, 32, 48, 3)
        assert count == 6 and fps == 8.0 and audio is not None
        out = load_video(outputs["2"][0])
        assert out["frames"].shape == (6, 32, 48, 3)
        assert out["audio"]["sample_rate"] == 16000

    def test_vhs_aliases_execute(self, tmp_path):
        """Reference workflow JSON naming the VideoHelperSuite node types
        runs unchanged; VHS-only inputs are tolerated."""
        from comfyui_distributed_tpu.graph.executor import GraphExecutor

        save_video(tmp_path / "in.mp4", smooth_frames(), fps=8.0)
        prompt = {
            "1": {"class_type": "VHS_LoadVideo", "inputs": {
                "video": "in.mp4", "force_rate": 0,
                "custom_width": 0, "custom_height": 0}},
            "2": {"class_type": "VHS_VideoCombine", "inputs": {
                "images": ["1", 0], "frame_rate": 8.0,
                "format": "video/h264-mp4", "loop_count": 0,
                "pingpong": False, "save_output": True,
                "filename_prefix": "combined"}},
        }
        outputs = GraphExecutor(self._ctx(tmp_path)).execute(prompt)
        out_path = outputs["2"][0]
        assert out_path.endswith(".mp4")
        assert load_video(out_path)["frames"].shape == (6, 32, 48, 3)

    def test_audioless_video_yields_empty_audio_dict(self, tmp_path):
        """No audio track → a valid zero-length AUDIO dict (not None),
        so downstream AUDIO consumers no-op instead of crashing."""
        from comfyui_distributed_tpu.graph.nodes_builtin import LoadVideo

        save_video(tmp_path / "silent.mp4", smooth_frames(), fps=8.0)
        _, audio, _, _ = LoadVideo().execute(video="silent.mp4",
                                             input_dir=str(tmp_path))
        assert audio["waveform"].shape == (1, 1, 0)

    def test_sidecar_namespace_is_uniqueness_checked(self, tmp_path):
        """A later save in a different format must not clobber an earlier
        video's audio sidecar (shared '<stem>.wav' namespace)."""
        from comfyui_distributed_tpu.graph.nodes_builtin import SaveVideo
        from comfyui_distributed_tpu.utils.audio_payload import wav_decode

        a, b = sine_audio(hz=440.0), sine_audio(hz=880.0)
        p1 = SaveVideo().execute(images=smooth_frames(), frame_rate=8.0,
                                 audio=a, format="mp4",
                                 output_dir=str(tmp_path))[0]
        SaveVideo().execute(images=smooth_frames(), frame_rate=8.0,
                            audio=b, format="webm",
                            output_dir=str(tmp_path))
        sidecar = wav_decode(
            (tmp_path / "video_00000.wav").read_bytes())
        np.testing.assert_allclose(sidecar["waveform"], a["waveform"],
                                   atol=1e-3)
        assert p1.endswith("video_00000.mp4")
        assert (tmp_path / "video_00001.webm").exists()
        assert (tmp_path / "video_00001.wav").exists()

    def test_save_video_unsupported_format(self, tmp_path):
        from comfyui_distributed_tpu.graph.nodes_builtin import SaveVideo

        with pytest.raises(ValidationError):
            SaveVideo().execute(images=smooth_frames(), frame_rate=8.0,
                                format="gif", output_dir=str(tmp_path))

    def test_load_video_missing_file(self, tmp_path):
        from comfyui_distributed_tpu.graph.nodes_builtin import LoadVideo

        with pytest.raises(ValidationError):
            LoadVideo().execute(video="nope.mp4",
                                input_dir=str(tmp_path))
