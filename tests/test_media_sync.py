"""Media sync tests (parity model: reference tests/api/test_media_sync.py —
path-conversion matrix + sync logic against mocked transports)."""

import asyncio
from pathlib import Path

import pytest

from comfyui_distributed_tpu.cluster import media_sync as ms


def run(coro):
    return asyncio.run(coro)


def prompt_with(image="photo.png", extra_inputs=None):
    inputs = {"image": image}
    inputs.update(extra_inputs or {})
    return {
        "1": {"class_type": "LoadImage", "inputs": inputs},
        "2": {"class_type": "SaveImage", "inputs": {"images": ["1", 0]}},
    }


class TestFindMediaRefs:
    def test_finds_image_input(self):
        refs = ms.find_media_refs(prompt_with("cat.png"))
        assert refs == [ms.MediaRef("1", "image", "cat.png")]

    def test_all_media_extensions(self):
        for ext in (".png", ".jpg", ".webp", ".mp4", ".wav", ".npz"):
            assert ms.looks_like_media(f"x{ext}")
            assert ms.looks_like_media(f"x{ext.upper()}")

    def test_non_media_value_ignored(self):
        refs = ms.find_media_refs(prompt_with("not a file"))
        assert refs == []

    def test_non_media_key_ignored(self):
        # a STRING prompt mentioning foo.png must not be synced
        p = {"1": {"class_type": "CLIPTextEncode",
                   "inputs": {"text": "a poster of foo.png"}}}
        assert ms.find_media_refs(p) == []

    def test_link_values_ignored(self):
        p = {"1": {"class_type": "X", "inputs": {"image": ["0", 0]}}}
        assert ms.find_media_refs(p) == []

    def test_video_and_audio_keys(self):
        p = {
            "1": {"class_type": "A", "inputs": {"video": "clip.mp4"}},
            "2": {"class_type": "B", "inputs": {"audio": "song.wav"}},
            "3": {"class_type": "C", "inputs": {"file": "arr.npz"}},
        }
        keys = {(r.node_id, r.input_key) for r in ms.find_media_refs(p)}
        assert keys == {("1", "video"), ("2", "audio"), ("3", "file")}


class TestConvertPaths:
    def test_unix_to_windows(self):
        p = prompt_with("subdir/cat.png")
        out = ms.convert_paths_for_platform(p, "\\")
        assert out["1"]["inputs"]["image"] == "subdir\\cat.png"

    def test_windows_to_unix(self):
        p = prompt_with("subdir\\cat.png")
        out = ms.convert_paths_for_platform(p, "/")
        assert out["1"]["inputs"]["image"] == "subdir/cat.png"

    def test_no_separator_untouched(self):
        p = prompt_with("cat.png")
        out = ms.convert_paths_for_platform(p, "\\")
        assert out["1"]["inputs"]["image"] == "cat.png"

    def test_original_not_mutated(self):
        p = prompt_with("a/b.png")
        ms.convert_paths_for_platform(p, "\\")
        assert p["1"]["inputs"]["image"] == "a/b.png"

    def test_bogus_separator_noop(self):
        p = prompt_with("a/b.png")
        assert ms.convert_paths_for_platform(p, "|") is p


class TestSyncHostMedia:
    @pytest.fixture
    def input_dir(self, tmp_path):
        (tmp_path / "photo.png").write_bytes(b"PNGDATA")
        return tmp_path

    def patch_transport(self, monkeypatch, *, exists=False, matches=False,
                        upload_ok=True, sep="/"):
        calls = {"check": [], "upload": []}

        async def fake_sep(host, timeout=10.0):
            return sep

        async def fake_check(host, rel, md5, timeout):
            calls["check"].append(rel)
            return exists and matches

        async def fake_upload(host, rel, path, timeout):
            calls["upload"].append((rel, path.read_bytes()))
            return upload_ok

        monkeypatch.setattr(ms, "fetch_host_path_separator", fake_sep)
        monkeypatch.setattr(ms, "_check_remote_file", fake_check)
        monkeypatch.setattr(ms, "_upload_file", fake_upload)
        return calls

    def test_uploads_on_miss(self, monkeypatch, input_dir):
        calls = self.patch_transport(monkeypatch, exists=False)
        out, report = run(ms.sync_host_media(
            {"id": "w0"}, prompt_with(), input_dir=input_dir))
        assert report.uploaded == 1 and report.skipped == 0
        assert calls["upload"] == [("photo.png", b"PNGDATA")]

    def test_skips_when_content_matches(self, monkeypatch, input_dir):
        calls = self.patch_transport(monkeypatch, exists=True, matches=True)
        out, report = run(ms.sync_host_media(
            {"id": "w0"}, prompt_with(), input_dir=input_dir))
        assert report.skipped == 1 and report.uploaded == 0
        assert calls["upload"] == []

    def test_missing_local_file_skipped(self, monkeypatch, input_dir):
        calls = self.patch_transport(monkeypatch)
        out, report = run(ms.sync_host_media(
            {"id": "w0"}, prompt_with("absent.png"), input_dir=input_dir))
        assert report.missing == 1
        assert calls["upload"] == [] and calls["check"] == []

    def test_upload_failure_reported(self, monkeypatch, input_dir):
        self.patch_transport(monkeypatch, upload_ok=False)
        out, report = run(ms.sync_host_media(
            {"id": "w0"}, prompt_with(), input_dir=input_dir))
        assert report.failed == ["photo.png"]

    def test_no_refs_short_circuits(self, monkeypatch):
        # transport must never be touched for a media-free prompt
        async def boom(*a, **k):
            raise AssertionError("transport touched")
        monkeypatch.setattr(ms, "fetch_host_path_separator", boom)
        p = {"1": {"class_type": "X", "inputs": {"seed": 1}}}
        out, report = run(ms.sync_host_media({"id": "w0"}, p))
        assert out is p and report.checked == 0

    def test_path_conversion_applied_to_result(self, monkeypatch, tmp_path):
        sub = tmp_path / "dir"
        sub.mkdir()
        (sub / "cat.png").write_bytes(b"X")
        self.patch_transport(monkeypatch, sep="\\")
        out, _ = run(ms.sync_host_media(
            {"id": "w0"}, prompt_with("dir/cat.png"), input_dir=tmp_path))
        assert out["1"]["inputs"]["image"] == "dir\\cat.png"

    def test_concurrency_bounded(self, monkeypatch, tmp_path):
        n = 8
        for i in range(n):
            (tmp_path / f"f{i}.png").write_bytes(b"D")
        p = {str(i): {"class_type": "LoadImage",
                      "inputs": {"image": f"f{i}.png"}} for i in range(n)}
        active = peak = 0

        async def fake_sep(host, timeout=10.0):
            return "/"

        async def fake_check(host, rel, md5, timeout):
            nonlocal active, peak
            active += 1
            peak = max(peak, active)
            await asyncio.sleep(0.01)
            active -= 1
            return True

        monkeypatch.setattr(ms, "fetch_host_path_separator", fake_sep)
        monkeypatch.setattr(ms, "_check_remote_file", fake_check)
        out, report = run(ms.sync_host_media(
            {"id": "w0"}, p, input_dir=tmp_path, concurrency=2))
        assert report.skipped == n
        assert peak <= 2


class TestServerRoutes:
    """check_file round-trip against the real aiohttp app."""

    def test_check_file_roundtrip(self, tmp_config, tmp_path, monkeypatch):
        from aiohttp.test_utils import TestClient, TestServer

        from comfyui_distributed_tpu.api.app import create_app
        from comfyui_distributed_tpu.cluster.controller import Controller

        monkeypatch.setenv("CDT_INPUT_DIR", str(tmp_path))
        (tmp_path / "a.png").write_bytes(b"HELLO")

        async def body():
            app = create_app(Controller())
            async with TestClient(TestServer(app)) as client:
                import hashlib
                md5 = hashlib.md5(b"HELLO").hexdigest()
                r = await client.post("/distributed/check_file",
                                      json={"path": "a.png", "md5": md5})
                body1 = await r.json()
                assert body1 == {"exists": True, "md5": md5, "matches": True}
                r = await client.post("/distributed/check_file",
                                      json={"path": "a.png", "md5": "0" * 32})
                assert (await r.json())["matches"] is False
                r = await client.post("/distributed/check_file",
                                      json={"path": "missing.png"})
                assert (await r.json()) == {"exists": False}
        run(body())
