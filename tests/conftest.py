"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

The TPU analogue of the reference's stub-package pattern (SURVEY §4): the
reference tests "multi-node" behavior against in-process asyncio queues; we
test multi-chip sharding against XLA's virtual CPU devices
(``--xla_force_host_platform_device_count=8``), so every sharded code path
compiles and executes without TPU hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment may have pre-registered an accelerator platform and set
# jax_platforms programmatically (which overrides the env var) — force CPU
# before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compile cache: XLA:CPU compiles of the model stacks dominate the
# suite's wall-clock (~2 h cold on this single-core host).  Caching compiled
# executables across runs turns the re-run cost into pure execution time.
# Same mechanism bench.py uses on the TPU (bench.py:90), separate directory so
# CPU test artifacts never mix with TPU ones.
from comfyui_distributed_tpu.utils.constants import TEST_XLA_CACHE  # noqa: E402

_cache_dir = TEST_XLA_CACHE.get()
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import faulthandler  # noqa: E402

import pytest  # noqa: E402

# Deadlock evidence (ISSUE 12): a lock inversion used to present as an
# opaque 870 s hang the outer `timeout -k` kills without a trace. Arm
# faulthandler so SIGABRT et al. dump all thread stacks, and give every
# test a watchdog that dumps stacks (repeating, without killing) once it
# runs past CDT_TEST_WATCHDOG_S — the hang still gets killed by the outer
# timeout, but now the log shows WHERE every thread was stuck.
faulthandler.enable()


@pytest.fixture(autouse=True)
def _stack_dump_watchdog():
    from comfyui_distributed_tpu.utils.constants import TEST_WATCHDOG_S

    secs = TEST_WATCHDOG_S.get()
    if secs and secs > 0:
        faulthandler.dump_traceback_later(secs, repeat=True)
        yield
        faulthandler.cancel_dump_traceback_later()
    else:
        yield


@pytest.fixture
def tmp_config(tmp_path, monkeypatch):
    """Point the config system at a throwaway file."""
    from comfyui_distributed_tpu.utils import config as config_mod

    path = tmp_path / "tpu_cluster_config.json"
    monkeypatch.setenv(config_mod.CONFIG_ENV, str(path))
    config_mod.invalidate_cache()
    yield path
    config_mod.invalidate_cache()


@pytest.fixture(autouse=True)
def _reset_resilience_state():
    """Circuit breakers and the fault plan are process-global by design
    (cluster/resilience.py, cluster/faults.py); without a reset, failures
    a test injects against 'w0' would quarantine 'w0' for every later
    test in the session."""
    from comfyui_distributed_tpu.cluster import faults, resilience
    from comfyui_distributed_tpu.cluster.elastic import states as _el_states
    from comfyui_distributed_tpu.lint import lockorder as _lockorder
    from comfyui_distributed_tpu.lint import loopstall as _loopstall

    resilience.BREAKERS.reset()
    _el_states.DRAIN.reset()
    _lockorder.reset()
    # arm the loop-stall sanitizer for the whole suite when the env asks
    # (the chaos suite exports CDT_LOOP_STALL=1); always drop recorded
    # stalls between tests so one slow callback can't fail its neighbors
    _loopstall.reset()
    faults.deactivate()
    yield
    resilience.BREAKERS.reset()
    _el_states.DRAIN.reset()
    faults.deactivate()


@pytest.fixture(autouse=True)
def _isolate_attn_table(tmp_path_factory, monkeypatch):
    """The attention tuning table (ops/autotune.py) persists next to the
    XLA cache by default; point every test at a throwaway path and drop
    the cached instance so no test reads another's sweeps (or a real
    /tmp leftover). The shipped in-repo layer still loads — that IS
    production behavior."""
    import sys

    monkeypatch.setenv(
        "CDT_ATTN_TABLE",
        str(tmp_path_factory.mktemp("attn") / "attn_tuning.json"))
    mod = sys.modules.get("comfyui_distributed_tpu.ops.autotune")
    if mod is not None:
        mod.reset_default_table()
    yield
    mod = sys.modules.get("comfyui_distributed_tpu.ops.autotune")
    if mod is not None:
        mod.reset_default_table()


@pytest.fixture(autouse=True)
def _isolate_content_cache(tmp_path_factory, monkeypatch):
    """The content cache (cluster/cache) persists next to the XLA cache
    by default; point every test at a throwaway directory so no test
    serves another's entries (or a real leftover). The in-memory tiers
    are per-Controller, so no global reset is needed."""
    monkeypatch.setenv(
        "CDT_CACHE_DIR", str(tmp_path_factory.mktemp("content_cache")))
    yield


@pytest.fixture
def fault_plan():
    """Activate a seeded FaultPlan for the test; returns an installer:
    ``plan = fault_plan("probe@0:drop;...")``."""
    from comfyui_distributed_tpu.cluster import faults

    def install(spec: str):
        return faults.activate(faults.FaultPlan.parse(spec))

    yield install
    faults.deactivate()
