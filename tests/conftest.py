"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

The TPU analogue of the reference's stub-package pattern (SURVEY §4): the
reference tests "multi-node" behavior against in-process asyncio queues; we
test multi-chip sharding against XLA's virtual CPU devices
(``--xla_force_host_platform_device_count=8``), so every sharded code path
compiles and executes without TPU hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment may have pre-registered an accelerator platform and set
# jax_platforms programmatically (which overrides the env var) — force CPU
# before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def tmp_config(tmp_path, monkeypatch):
    """Point the config system at a throwaway file."""
    from comfyui_distributed_tpu.utils import config as config_mod

    path = tmp_path / "tpu_cluster_config.json"
    monkeypatch.setenv(config_mod.CONFIG_ENV, str(path))
    config_mod.invalidate_cache()
    yield path
    config_mod.invalidate_cache()
