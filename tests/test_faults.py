"""Fault-injection harness tests (cluster/faults.py): spec grammar,
deterministic injection, and the aiohttp session wrapper over a real
localhost server. All chaos-marked: scripts/chaos_suite.sh runs them as
the dedicated lane; they are fast, so tier-1 picks them up too."""

import asyncio

import pytest

from comfyui_distributed_tpu.cluster import faults
from comfyui_distributed_tpu.cluster.faults import (
    Fault, FaultPlan, FaultSpecError, op_for_url)

pytestmark = pytest.mark.chaos


def run(coro):
    return asyncio.run(coro)


class TestSpecGrammar:
    def test_full_grammar(self):
        plan = FaultPlan.parse(
            "seed=7;probe@0-1:drop;submit@3:corrupt;"
            "heartbeat@*:silence;request_work@%0.25:http500=503;"
            "dispatch@0,2:latency=0.01")
        assert plan.seed == 7
        kinds = {(f.op, f.kind) for f in plan.faults}
        assert ("probe", "drop") in kinds
        assert ("request_work", "http500") in kinds
        lat = next(f for f in plan.faults if f.kind == "latency")
        assert lat.indices == frozenset({0, 2}) and lat.value == 0.01
        http = next(f for f in plan.faults if f.kind == "http500")
        assert http.prob == 0.25 and http.value == 503.0

    def test_empty_and_whitespace_clauses_ignored(self):
        plan = FaultPlan.parse(" ; probe@0:drop ;; ")
        assert len(plan.faults) == 1

    @pytest.mark.parametrize("bad", [
        "probe@0",                      # no kind
        "probe@0:explode",              # unknown kind
        "probe@x:drop",                 # bad index
        "probe@5-2:drop",               # empty range
        "probe@%1.5:drop",              # probability out of range
        "seed=abc",                     # bad seed
    ])
    def test_malformed_specs_raise(self, bad):
        with pytest.raises(FaultSpecError):
            FaultPlan.parse(bad)

    def test_op_for_url(self):
        assert op_for_url("http://h:1/distributed/health") == "probe"
        assert op_for_url("http://h:1/prompt") == "dispatch"
        assert op_for_url("http://h:1/distributed/worker_ws") == "dispatch"
        assert op_for_url("http://h:1/distributed/request_image") == \
            "request_work"
        assert op_for_url("http://h:1/distributed/submit_tiles") == "submit"
        assert op_for_url("http://h:1/distributed/heartbeat") == "heartbeat"
        assert op_for_url("http://h:1/distributed/job_status?job_id=j") == \
            "job_status"
        assert op_for_url("http://h:1/whatever") == "http"


class TestDeterminism:
    def test_index_selectors_fire_at_exact_calls(self):
        plan = FaultPlan.parse("probe@1,3:drop")
        hits = [plan.next_fault("probe") is not None for _ in range(5)]
        assert hits == [False, True, False, True, False]
        # other ops keep their own counters
        assert plan.next_fault("submit") is None

    def test_probability_selector_replays_with_same_seed(self):
        def draw():
            plan = FaultPlan.parse("seed=42;submit@%0.5:drop")
            return [plan.next_fault("submit") is not None
                    for _ in range(32)]

        a, b = draw(), draw()
        assert a == b               # seeded => identical run-to-run
        assert any(a) and not all(a)

    def test_star_op_matches_everything(self):
        plan = FaultPlan.parse("*@0:drop")
        assert plan.next_fault("probe") is not None
        assert plan.next_fault("submit") is not None   # its own index 0
        assert plan.next_fault("probe") is None

    def test_injection_journal(self):
        plan = FaultPlan.parse("probe@0:drop;submit@1:http500")
        plan.next_fault("probe")
        plan.next_fault("submit")
        plan.next_fault("submit")
        assert plan.injected == [("probe", 0, "drop"),
                                 ("submit", 1, "http500")]

    def test_corrupt_bytes_flips_exactly_one_byte(self):
        plan = FaultPlan([], seed=3)
        data = bytes(range(64))
        bad = plan.corrupt_bytes(data)
        assert len(bad) == len(data)
        assert sum(a != b for a, b in zip(data, bad)) == 1
        assert FaultPlan.truncate_bytes(data) == data[:32]


class TestActivation:
    def test_env_spec_activates(self, monkeypatch):
        monkeypatch.setenv(faults.FAULTS_ENV, "seed=5;probe@0:drop")
        faults.deactivate()          # force env re-read
        plan = faults.active_plan()
        assert plan is not None and plan.seed == 5
        faults.deactivate()
        monkeypatch.delenv(faults.FAULTS_ENV)
        assert faults.active_plan() is None

    def test_wrap_session_is_identity_when_inactive(self):
        sentinel = object()
        assert faults.wrap_session(sentinel) is sentinel


class TestSessionWrapper:
    """Faults over a real localhost aiohttp server."""

    def _serve(self):
        from aiohttp import web
        from aiohttp.test_utils import TestClient, TestServer

        calls = {"n": 0, "bodies": []}

        async def echo(request):
            calls["n"] += 1
            calls["bodies"].append(await request.read())
            return web.json_response({"ok": True, "n": calls["n"]})

        app = web.Application()
        app.router.add_post("/distributed/heartbeat", echo)
        app.router.add_post("/distributed/submit_tiles", echo)
        app.router.add_get("/distributed/health", echo)
        return calls, TestClient(TestServer(app))

    def test_drop_latency_500_silence(self, fault_plan):
        import aiohttp

        plan = fault_plan("heartbeat@0:drop;heartbeat@1:http500=502;"
                          "heartbeat@2:silence")

        async def body():
            calls, client = self._serve()
            async with client:
                base = f"http://127.0.0.1:{client.port}"
                session = faults.wrap_session(client.session)
                url = f"{base}/distributed/heartbeat"
                # call 0: dropped before the wire
                with pytest.raises(aiohttp.ClientConnectionError):
                    async with session.post(url, json={}):
                        pass
                # call 1: synthetic 502, never reaches the server
                async with session.post(url, json={}) as resp:
                    assert resp.status == 502
                # call 2: silenced — fake 200, server never sees it
                async with session.post(url, json={}) as resp:
                    assert resp.status == 200
                    assert (await resp.json())["status"] == "ok"
                assert calls["n"] == 0
                # call 3: no fault left — real round trip
                async with session.post(url, json={}) as resp:
                    assert (await resp.json())["ok"] is True
                assert calls["n"] == 1
            assert [k for _, _, k in plan.injected] == \
                ["drop", "http500", "silence"]
        run(body())

    def test_corrupt_mutates_formdata_frame_only(self, fault_plan):
        import json

        import aiohttp

        fault_plan("submit@0:corrupt")

        async def body():
            calls, client = self._serve()
            async with client:
                base = f"http://127.0.0.1:{client.port}"
                session = faults.wrap_session(client.session)
                frame = bytes(range(256)) * 4

                def form():
                    f = aiohttp.FormData()
                    f.add_field("tiles_metadata",
                                json.dumps({"job_id": "j"}),
                                content_type="application/json")
                    f.add_field("tile_0", frame, filename="tile_0.cdtf",
                                content_type="application/x-cdt-frame")
                    return f

                url = f"{base}/distributed/submit_tiles"
                async with session.post(url, data=form()) as resp:
                    assert resp.status == 200
                async with session.post(url, data=form()) as resp:
                    assert resp.status == 200
                first, second = calls["bodies"]
                # metadata survived intact both times
                assert b'{"job_id": "j"}' in first
                assert b'{"job_id": "j"}' in second
                # the frame bytes differ exactly once (call 0 corrupted)
                assert first != second
                assert frame in second and frame not in first
        run(body())

    def test_latency_defers_but_delivers(self, fault_plan):
        import time

        fault_plan("probe@0:latency=0.2")

        async def body():
            calls, client = self._serve()
            async with client:
                base = f"http://127.0.0.1:{client.port}"
                session = faults.wrap_session(client.session)
                t0 = time.monotonic()
                async with session.get(
                        f"{base}/distributed/health") as resp:
                    assert resp.status == 200
                assert time.monotonic() - t0 >= 0.2
                assert calls["n"] == 1
        run(body())


class TestFaultyJobStore:
    def test_store_ops_consult_plan(self):
        from comfyui_distributed_tpu.cluster.faults import FaultyJobStore
        from comfyui_distributed_tpu.cluster.job_store import JobStore

        async def body():
            plan = FaultPlan.parse(
                "store.request_work@0:drop;store.submit@0:silence;"
                "store.heartbeat@*:drop")
            store = FaultyJobStore(JobStore(), plan)
            await store.init_tile_job("j", 2, chunk=1)
            assert await store.request_work("j", "w0") is None  # dropped
            task = await store.request_work("j", "w0")          # real
            assert task is not None
            assert not await store.submit_result(                # swallowed
                "j", "w0", task["task_id"], {"x": 1})
            assert task["task_id"] not in store.tile_jobs["j"].completed
            assert await store.submit_result(                    # real
                "j", "w0", task["task_id"], {"x": 1})
            assert not await store.heartbeat("j", "w0")          # silenced
        run(body())
