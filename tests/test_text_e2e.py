"""Real-tokenizer conditioning end-to-end (VERDICT r04 weak #4).

Prompt string → CLIP BPE tokenizer (synthetic vocab via
``CDT_TOKENIZER_DIR``) → weight-faithful CLIP-L/G stack → UNet sampling →
image, through the graph executor — the exact production path a user with
a real ``vocab.json``/``merges.txt`` gets, previously only tested in
pieces (tokenizer differentially in ``test_tokenizer.py``, CLIP numerics
in ``test_clip.py``, sampling in ``test_workflows.py``) but never wired
together.

The synthetic vocabulary places EOT/SOT at the top of a fixed-size table
so pooling (``argmax(tokens == eot_token_id)``) is exercised with the same
id discipline real CLIP vocabs use (eot = vocab_size - 1 = 49407)."""

import json
from pathlib import Path

import jax
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.executor import GraphExecutor, strip_meta
from comfyui_distributed_tpu.models.clip import (
    CLIPConditioner, CLIPTextConfig, CLIPTextModel, SDXLTextStack)
from comfyui_distributed_tpu.models.tokenizer import (
    CLIPBPETokenizer, EOT, SOT)

pytestmark = pytest.mark.slow  # compile-heavy: builds/jits real model stacks

VOCAB_SIZE = 128                 # matches CLIPTextConfig.tiny()
EOT_ID = VOCAB_SIZE - 1          # real-CLIP convention: EOT is the last id
MAX_LEN = 16                     # matches CLIPTextConfig.tiny()


def _build_vocab() -> tuple[dict, list]:
    """letters (bare + ``</w>``), a few merges, filler to pin EOT at 127."""
    vocab: dict[str, int] = {}
    for c in "abcdefghijklmnopqrstuvwxyz":
        vocab[c] = len(vocab)
        vocab[c + "</w>"] = len(vocab)
    merges = [("c", "a"), ("ca", "t</w>"), ("d", "o"), ("do", "g</w>"),
              ("s", "e"), ("se", "a</w>")]
    for a, b in merges:
        vocab[a + b] = len(vocab)
    while len(vocab) < VOCAB_SIZE - 2:
        vocab[f"<fill{len(vocab)}>"] = len(vocab)
    vocab[SOT] = VOCAB_SIZE - 2
    vocab[EOT] = EOT_ID
    return vocab, merges


@pytest.fixture(scope="module")
def vocab_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("clip_vocab")
    vocab, merges = _build_vocab()
    (d / "vocab.json").write_text(json.dumps(vocab))
    (d / "merges.txt").write_text(
        "#version: 0.2\n" + "\n".join(f"{a} {b}" for a, b in merges))
    return d


def _tiny_stack() -> SDXLTextStack:
    """Tiny SDXL dual-tower whose concat context (16+16=32) and projected
    pool (16) match the ``tiny`` registry preset's UNet contract."""
    cfg_l = CLIPTextConfig.tiny(width=16, heads=2, eot_token_id=EOT_ID)
    cfg_g = CLIPTextConfig.tiny(width=16, heads=2, act="gelu",
                                projection_dim=16, eot_token_id=EOT_ID)
    k1, k2 = jax.random.split(jax.random.key(0))
    return SDXLTextStack(CLIPTextModel(cfg_l).init(k1),
                         CLIPTextModel(cfg_g).init(k2))


class TestConditionerTokenizerWiring:
    def test_single_explicit_tokenizer_raises_descriptively(self,
                                                            vocab_dir):
        """Passing only one of tok_l/tok_g used to crash vocab validation
        on None.eot_id (advisor r05) — now it's a clear ValueError
        requiring the pair."""
        tok = CLIPBPETokenizer.from_dir(vocab_dir, max_len=MAX_LEN)
        with pytest.raises(ValueError, match="both tok_l and tok_g"):
            CLIPConditioner(_tiny_stack(), kind="sdxl", tok_l=tok)
        with pytest.raises(ValueError, match="both tok_l and tok_g"):
            CLIPConditioner(_tiny_stack(), kind="sdxl", tok_g=tok)
        # the pair still works
        cond = CLIPConditioner(_tiny_stack(), kind="sdxl", tok_l=tok,
                               tok_g=CLIPBPETokenizer.from_dir(
                                   vocab_dir, max_len=MAX_LEN,
                                   pad_token_id=0))
        assert cond.tok_l is tok

    def test_sd3_stack_single_tokenizer_raises(self, vocab_dir):
        from comfyui_distributed_tpu.models.t5 import SD3TextStack

        tok = CLIPBPETokenizer.from_dir(vocab_dir, max_len=MAX_LEN)
        stack_parts = SD3TextStack.init_random(jax.random.key(0),
                                               tiny=True)
        with pytest.raises(ValueError, match="both tok_l and tok_g"):
            SD3TextStack(stack_parts.clip_l, stack_parts.clip_g,
                         stack_parts.t5, tok_l=tok)

    def test_loads_at_stack_max_len(self, vocab_dir, monkeypatch):
        """The conditioner must tokenize to the stack's context length —
        a 77-padded sequence does not shape-check against the tiny
        towers' 16-entry position table."""
        monkeypatch.setenv("CDT_TOKENIZER_DIR", str(vocab_dir))
        cond = CLIPConditioner(_tiny_stack(), kind="sdxl")
        assert cond.tok_l is not None and cond.tok_g is not None
        assert cond.tok_l.max_len == MAX_LEN
        assert cond.tok_g.pad_token_id == 0          # CLIP-G zero padding
        assert cond.tok_l.pad_token_id == EOT_ID     # CLIP-L EOT padding

    def test_ids_match_reference_tokenizer(self, vocab_dir, monkeypatch):
        monkeypatch.setenv("CDT_TOKENIZER_DIR", str(vocab_dir))
        cond = CLIPConditioner(_tiny_stack(), kind="sdxl")
        direct = CLIPBPETokenizer.from_dir(vocab_dir, max_len=MAX_LEN)
        ids = cond._ids(["cat dog"], cond.tok_l,
                        cond.stack.clip_l.config, EOT_ID)
        assert ids.tolist()[0] == direct.encode("cat dog")
        # the BPE merges actually engaged (whole-word tokens, not letters)
        assert direct.encode("cat dog")[1:3] == [
            direct.vocab["cat</w>"], direct.vocab["dog</w>"]]

    def test_encode_shapes_and_prompt_sensitivity(self, vocab_dir,
                                                  monkeypatch):
        monkeypatch.setenv("CDT_TOKENIZER_DIR", str(vocab_dir))
        cond = CLIPConditioner(_tiny_stack(), kind="sdxl")
        ctx, pooled = cond.encode(["cat dog"])
        assert ctx.shape == (1, MAX_LEN, 32) and pooled.shape == (1, 16)
        ctx2, pooled2 = cond.encode(["sea cat"])
        assert not np.allclose(np.asarray(ctx), np.asarray(ctx2))
        assert not np.allclose(np.asarray(pooled), np.asarray(pooled2))
        # whitespace/case normalization is the tokenizer's, not the hash
        # fallback's: same tokens → bitwise-identical conditioning
        ctx3, _ = cond.encode(["  CAT   dog "])
        np.testing.assert_array_equal(np.asarray(ctx), np.asarray(ctx3))


class TestPromptToImage:
    def test_txt2img_workflow_real_tokenizer(self, vocab_dir, monkeypatch,
                                             tmp_path):
        """The shipped txt2img graph, conditioned through the real BPE →
        CLIP-L/G path end-to-end: string prompts in, per-chip PNGs out."""
        from comfyui_distributed_tpu.models.registry import ModelRegistry

        monkeypatch.setenv("CDT_TOKENIZER_DIR", str(vocab_dir))
        registry = ModelRegistry()
        bundle = registry.get("tiny")
        stack = _tiny_stack()
        bundle.clip_stack = stack
        bundle.text_encoder = CLIPConditioner(stack, kind="sdxl")
        assert bundle.text_encoder.tok_l is not None

        prompt = strip_meta(json.loads(
            Path("workflows/distributed-txt2img.json").read_text()))
        for node in prompt.values():
            if node["class_type"] == "CheckpointLoader":
                node["inputs"]["ckpt_name"] = "tiny"
            for key, val in (("width", 16), ("height", 16), ("steps", 2)):
                if key in node.get("inputs", {}):
                    node["inputs"][key] = val
        prompt["2"]["inputs"]["text"] = "cat dog sea"
        prompt["3"]["inputs"]["text"] = "dog"
        prompt["7"]["inputs"]["output_dir"] = str(tmp_path)

        outputs = GraphExecutor({"model_registry": registry}).execute(prompt)
        n_dev = len(jax.devices())
        imgs = np.asarray(outputs["6"][0])
        assert imgs.shape[0] == n_dev
        assert np.isfinite(imgs).all()
        assert len(list(tmp_path.glob("*.png"))) == n_dev
