"""Native data-plane library tests: frame codec (native + pure-python
paths, cross-interop), compositing, hashing, and the binary-frame
collector route."""

import asyncio

import numpy as np
import pytest

from comfyui_distributed_tpu import native


def run(coro):
    return asyncio.run(coro)


@pytest.fixture
def no_native(monkeypatch):
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_load_attempted", True)


toolchain = pytest.mark.skipif(not native.is_native(),
                               reason="native library unavailable")


class TestFrameCodec:
    @pytest.mark.parametrize("dtype", [np.uint8, np.float32, np.int32])
    def test_roundtrip_python(self, no_native, dtype):
        a = (np.random.RandomState(0).rand(5, 7, 3) * 100).astype(dtype)
        assert np.array_equal(native.unpack_frame(native.pack_frame(a)), a)

    def test_raw_level0(self, no_native):
        a = np.arange(100, dtype=np.float32)
        f = native.pack_frame(a, level=0)
        assert np.array_equal(native.unpack_frame(f), a)

    def test_compression_shrinks_constant_data(self, no_native):
        a = np.zeros((256, 256, 3), np.uint8)
        f = native.pack_frame(a, level=1)
        assert len(f) < a.nbytes // 10

    def test_bfloat16_roundtrips_losslessly(self, no_native):
        import jax.numpy as jnp

        a = np.asarray(jnp.arange(8, dtype=jnp.bfloat16))
        out = native.unpack_frame(native.pack_frame(a))
        assert out.dtype == a.dtype
        assert np.array_equal(out.view(np.uint16), a.view(np.uint16))

    @pytest.mark.parametrize("dtype", [np.int64, np.float64, np.bool_])
    def test_wide_dtypes_roundtrip(self, no_native, dtype):
        a = np.arange(16).reshape(4, 4).astype(dtype)
        out = native.unpack_frame(native.pack_frame(a))
        assert out.dtype == a.dtype
        assert np.array_equal(out, a)

    def test_unsupported_dtype_raises(self, no_native):
        with pytest.raises(ValueError, match="unsupported frame dtype"):
            native.pack_frame(np.zeros(4, np.complex64))

    def test_rawlen_bomb_rejected(self, no_native, monkeypatch):
        """A frame header claiming a huge raw size must be rejected before
        allocation (zlib-bomb / memory-exhaustion guard)."""
        a = np.zeros((4, 4), np.float32)
        f = bytearray(native.pack_frame(a, level=0))
        # raw_len lives in the last 8 header bytes before the payload
        off = 8 + 8 * 2 + 4 + 8
        f[off:off + 8] = (1 << 60).to_bytes(8, "little")
        with pytest.raises(ValueError, match="raw size"):
            native.unpack_frame(bytes(f))

    def test_shape_size_mismatch_rejected(self, no_native):
        a = np.zeros((4, 4), np.float32)
        f = bytearray(native.pack_frame(a, level=0))
        f[8:16] = (1 << 50).to_bytes(8, "little")   # dim0 → absurd
        with pytest.raises(ValueError, match="raw size"):
            native.unpack_frame(bytes(f))

    def test_inflation_bomb_bounded(self, no_native):
        """A payload that INFLATES beyond its declared raw_len must fail
        without materializing the expansion (decompress is bounded by the
        header's raw_len, which the shape check already pinned)."""
        big = native.pack_frame(np.zeros((512, 512, 3), np.float32), level=1)
        small_hdr = native.pack_frame(np.zeros((4, 4), np.float32), level=1)
        # graft the big compressed payload onto the small header: header
        # claims 64 raw bytes, payload inflates to 3 MB
        hdr_len = 8 + 8 * 2 + 4 + 8 + 8
        big_payload = big[8 + 8 * 3 + 4 + 8 + 8:]
        f = bytearray(small_hdr[:hdr_len])
        f[7] |= 1                                     # flags: compressed
        stored_off = 8 + 8 * 2 + 4
        f[stored_off:stored_off + 8] = len(big_payload).to_bytes(8, "little")
        with pytest.raises(ValueError, match="crc mismatch|decompress"):
            native.unpack_frame(bytes(f) + big_payload)

    def test_corrupt_payload_detected(self, no_native):
        a = np.arange(64, dtype=np.float32)
        f = bytearray(native.pack_frame(a, level=0))
        f[-2] ^= 0xFF
        with pytest.raises(ValueError):
            native.unpack_frame(bytes(f))

    def test_not_a_frame(self, no_native):
        with pytest.raises(ValueError):
            native.unpack_frame(b"PNG....definitely not a frame")

    @toolchain
    def test_native_roundtrip(self):
        a = (np.random.RandomState(1).rand(33, 65, 3) * 255).astype(np.uint8)
        f = native.pack_frame(a, level=1)
        assert np.array_equal(native.unpack_frame(f), a)

    @toolchain
    def test_cross_interop(self, monkeypatch):
        """Native-packed frames unpack in pure python and vice versa —
        mixed clusters (a host without a toolchain) stay wire-compatible."""
        a = (np.random.RandomState(2).rand(16, 16, 3) * 255).astype(np.uint8)
        f_native = native.pack_frame(a, level=1)
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_load_attempted", True)
        assert np.array_equal(native.unpack_frame(f_native), a)
        f_py = native.pack_frame(a, level=1)
        monkeypatch.undo()
        assert np.array_equal(native.unpack_frame(f_py), a)

    @toolchain
    def test_corrupt_detected_native(self):
        a = np.arange(64, dtype=np.float32)
        f = bytearray(native.pack_frame(a, level=0))
        f[-2] ^= 0xFF
        with pytest.raises(ValueError, match="-5"):
            native.unpack_frame(bytes(f))


class TestHash:
    def test_known_value(self, no_native):
        # FNV-1a 64 of empty input is the offset basis
        assert native.hash64(b"") == 14695981039346656037

    @toolchain
    def test_native_matches_python(self):
        data = b"the quick brown fox"
        native_h = native.hash64(data)
        h = 14695981039346656037
        for b in data:
            h = ((h ^ b) * 1099511628211) & 0xFFFFFFFFFFFFFFFF
        assert native_h == h


class TestCompositing:
    def _numpy_blend(self, canvas, tile, mask, y, x):
        out = canvas.copy()
        th, tw = mask.shape
        m = mask[..., None]
        out[y:y + th, x:x + tw] = (out[y:y + th, x:x + tw] * (1 - m)
                                   + tile * m)
        return out

    @pytest.mark.parametrize("use_native", [False, True])
    def test_blend_matches_numpy(self, use_native, monkeypatch):
        if use_native and not native.is_native():
            pytest.skip("native library unavailable")
        if not use_native:
            monkeypatch.setattr(native, "_lib", None)
            monkeypatch.setattr(native, "_load_attempted", True)
        rs = np.random.RandomState(3)
        canvas = np.ascontiguousarray(rs.rand(32, 32, 3), np.float32)
        tile = rs.rand(8, 8, 3).astype(np.float32)
        mask = rs.rand(8, 8).astype(np.float32)
        expect = self._numpy_blend(canvas, tile, mask, 4, 6)
        native.blend_tile(canvas, tile, mask, 4, 6)
        np.testing.assert_allclose(canvas, expect, atol=1e-6)

    def test_blend_clips_out_of_bounds(self):
        canvas = np.zeros((16, 16, 3), np.float32)
        tile = np.ones((8, 8, 3), np.float32)
        mask = np.ones((8, 8), np.float32)
        native.blend_tile(canvas, tile, mask, 12, 12)   # extends past edge
        assert canvas[12:, 12:].min() == 1.0
        assert canvas[:12].max() == 0.0

    def test_accumulate_normalizes(self):
        canvas_acc = np.zeros((16, 16, 3), np.float32)
        wsum = np.zeros((16, 16), np.float32)
        tile = np.full((8, 8, 3), 2.0, np.float32)
        mask = np.full((8, 8), 0.5, np.float32)
        native.accumulate_tile(canvas_acc, wsum, tile, mask, 0, 0)
        native.accumulate_tile(canvas_acc, wsum, tile, mask, 0, 4)  # overlap
        out = canvas_acc / np.maximum(wsum, 1e-8)[..., None]
        np.testing.assert_allclose(out[:8, :8], 2.0, atol=1e-5)

    def test_dtype_guard(self):
        with pytest.raises(ValueError, match="contiguous float32"):
            native.blend_tile(np.zeros((4, 4, 3)), np.zeros((2, 2, 3), np.float32),
                              np.zeros((2, 2), np.float32), 0, 0)


class TestFramesRoute:
    def test_frames_transport_end_to_end(self, tmp_config):
        """Worker bridge sends binary frames → master route ingests →
        collector drain combines (the full cross-host data plane)."""
        import aiohttp
        from aiohttp.test_utils import TestClient, TestServer

        from comfyui_distributed_tpu.api.app import create_app
        from comfyui_distributed_tpu.cluster.controller import Controller
        from comfyui_distributed_tpu.cluster.collector_bridge import CollectorBridge

        async def body():
            controller = Controller()
            app = create_app(controller)
            async with TestClient(TestServer(app)) as client:
                images = np.stack([
                    np.full((8, 8, 3), 0.25, np.float32),
                    np.full((8, 8, 3), 0.75, np.float32),
                ])
                await controller.store.prepare_collector_job("jobF", ("w0",))

                bridge = CollectorBridge(controller.store,
                                         asyncio.get_running_loop())
                master_url = f"http://127.0.0.1:{client.port}"
                # patch session getter to the test client's session
                import comfyui_distributed_tpu.cluster.collector_bridge as cb

                class S:
                    def post(self, url, **kw):
                        path = url.split(str(client.port))[1]
                        return client.session.post(client.make_url(path),
                                                   **kw)
                orig = cb.get_client_session
                cb.get_client_session = lambda: S()

                async def no_legacy(*a, **k):
                    raise AssertionError(
                        "legacy envelope path used — frames transport "
                        "should have handled the send")
                bridge._post_with_retry = no_legacy
                try:
                    await bridge.send_async("jobF", "w0", images, None,
                                            master_url)
                    combined, audio = await bridge.collect_async(
                        "jobF", np.full((1, 8, 8, 3), 0.5, np.float32),
                        None, enabled_worker_ids=("w0",))
                finally:
                    cb.get_client_session = orig
                assert combined.shape == (3, 8, 8, 3)
                # master first, then worker frames in batch order
                np.testing.assert_allclose(combined[0], 0.5, atol=1e-6)
                np.testing.assert_allclose(combined[1], 0.25, atol=2e-2)
                np.testing.assert_allclose(combined[2], 0.75, atol=2e-2)
        run(body())

    def test_bad_frame_rejected(self, tmp_config):
        import aiohttp
        from aiohttp.test_utils import TestClient, TestServer

        from comfyui_distributed_tpu.api.app import create_app
        from comfyui_distributed_tpu.cluster.controller import Controller

        async def body():
            app = create_app(Controller())
            async with TestClient(TestServer(app)) as client:
                form = aiohttp.FormData()
                form.add_field("metadata",
                               '{"job_id": "j", "worker_id": "w", "count": 1}',
                               content_type="application/json")
                form.add_field("frame_0", b"garbage-not-a-frame",
                               filename="frame_0.cdtf",
                               content_type="application/x-cdt-frame")
                r = await client.post("/distributed/job_complete_frames",
                                      data=form, headers={"X-CDT-Client": "1"})
                assert r.status == 400
                assert "frame 0" in (await r.json())["error"]
        run(body())
