"""ControlNet: torch-replica conversion differential, zero-init identity,
pipeline/control threading, and the loader/apply nodes.

Parity target: the reference relies on ComfyUI ControlNet and crops
hints per tile (``/root/reference/utils/usdu_utils.py:506``)."""

import numpy as np
import pytest
import torch
import torch.nn as tnn

import jax
import jax.numpy as jnp

from comfyui_distributed_tpu.models.controlnet import (
    ControlNet, ControlNetBundle, init_controlnet)
from comfyui_distributed_tpu.models.convert import (
    ConversionError, convert_controlnet)
from comfyui_distributed_tpu.models.registry import ModelRegistry
from comfyui_distributed_tpu.models.unet import UNetConfig

from test_convert import (  # torch replica building blocks
    TDownsample, TResBlock, TSpatialTransformer, t_timestep_embedding)

pytestmark = pytest.mark.slow  # compile-heavy: builds/jits real model stacks


# ---------------------------------------------------------------------------
# torch replica: LDM cldm ControlNet
# ---------------------------------------------------------------------------

class TControlNet(tnn.Module):
    def __init__(self, cfg: UNetConfig, ctx_dim: int, hint_ch: int = 3):
        super().__init__()
        self.cfg = cfg
        time_dim = cfg.model_channels * 4
        self.time_embed = tnn.Sequential(
            tnn.Linear(cfg.model_channels, time_dim), tnn.SiLU(),
            tnn.Linear(time_dim, time_dim))
        if cfg.adm_in_channels:
            self.label_emb = tnn.Sequential(tnn.Sequential(
                tnn.Linear(cfg.adm_in_channels, time_dim), tnn.SiLU(),
                tnn.Linear(time_dim, time_dim)))

        self.input_hint_block = tnn.Sequential(
            tnn.Conv2d(hint_ch, 16, 3, padding=1), tnn.SiLU(),
            tnn.Conv2d(16, 16, 3, padding=1), tnn.SiLU(),
            tnn.Conv2d(16, 32, 3, padding=1, stride=2), tnn.SiLU(),
            tnn.Conv2d(32, 32, 3, padding=1), tnn.SiLU(),
            tnn.Conv2d(32, 96, 3, padding=1, stride=2), tnn.SiLU(),
            tnn.Conv2d(96, 96, 3, padding=1), tnn.SiLU(),
            tnn.Conv2d(96, 256, 3, padding=1, stride=2), tnn.SiLU(),
            tnn.Conv2d(256, cfg.model_channels, 3, padding=1))

        def st(ch, depth):
            return TSpatialTransformer(ch, ctx_dim, cfg.heads_for(ch), depth)

        blocks = [tnn.ModuleList([tnn.Conv2d(cfg.in_channels,
                                             cfg.model_channels, 3,
                                             padding=1)])]
        zeros = [tnn.Sequential(tnn.Conv2d(cfg.model_channels,
                                           cfg.model_channels, 1))]
        ch = cfg.model_channels
        for level, mult in enumerate(cfg.channel_mult):
            out_ch = cfg.model_channels * mult
            for _ in range(cfg.num_res_blocks):
                mods = [TResBlock(ch, out_ch, time_dim)]
                if cfg.transformer_depth[level]:
                    mods.append(st(out_ch, cfg.transformer_depth[level]))
                blocks.append(tnn.ModuleList(mods))
                ch = out_ch
                zeros.append(tnn.Sequential(tnn.Conv2d(ch, ch, 1)))
            if level < len(cfg.channel_mult) - 1:
                blocks.append(tnn.ModuleList([TDownsample(ch)]))
                zeros.append(tnn.Sequential(tnn.Conv2d(ch, ch, 1)))
        self.input_blocks = tnn.ModuleList(blocks)
        self.zero_convs = tnn.ModuleList(zeros)

        mid = [TResBlock(ch, ch, time_dim)]
        if cfg.transformer_depth[-1]:
            mid.append(st(ch, cfg.transformer_depth[-1]))
        mid.append(TResBlock(ch, ch, time_dim))
        self.middle_block = tnn.ModuleList(mid)
        self.middle_block_out = tnn.Sequential(tnn.Conv2d(ch, ch, 1))

    def forward(self, x, t, ctx, y, hint):
        emb = self.time_embed(t_timestep_embedding(t, self.cfg.model_channels))
        if self.cfg.adm_in_channels:
            emb = emb + self.label_emb(y)
        guided = self.input_hint_block(hint)
        h = x
        outs = []
        for i, mods in enumerate(self.input_blocks):
            for m in mods:
                if isinstance(m, TResBlock):
                    h = m(h, emb)
                elif isinstance(m, TSpatialTransformer):
                    h = m(h, ctx)
                else:
                    h = m(h)
            if i == 0:
                h = h + guided
            outs.append(self.zero_convs[i](h))
        for m in self.middle_block:
            h = m(h, emb) if isinstance(m, TResBlock) else m(h, ctx)
        outs.append(self.middle_block_out(h))
        return outs


def _nchw(x):
    return torch.from_numpy(np.asarray(x, np.float32).transpose(0, 3, 1, 2))


def _nhwc(x):
    return x.detach().numpy().transpose(0, 2, 3, 1)


@pytest.fixture(scope="module")
def pair():
    cfg = UNetConfig.tiny(dtype="float32")
    torch.manual_seed(0)
    tmodel = TControlNet(cfg, ctx_dim=cfg.context_dim).eval()
    # trained checkpoints have non-zero "zero" convs — randomize them so
    # the differential test exercises real residuals
    with torch.no_grad():
        for z in list(tmodel.zero_convs) + [tmodel.middle_block_out]:
            z[0].weight.normal_(0, 0.05)
            z[0].bias.normal_(0, 0.05)
    sd = {f"control_model.{k}": v.numpy()
          for k, v in tmodel.state_dict().items()}
    bundle = init_controlnet(cfg, jax.random.key(0), sample_shape=(8, 8, 4),
                             context_len=8)
    params = convert_controlnet(sd, bundle.params, cfg)
    model = ControlNet(UNetConfig.tiny(dtype="float32"))
    return cfg, tmodel, ControlNetBundle(model, params), sd


class TestConversion:
    def test_residuals_match_torch(self, pair):
        cfg, tmodel, bundle, _ = pair
        rng = np.random.RandomState(1)
        x = rng.randn(2, 8, 8, 4).astype(np.float32)
        t = np.array([5.0, 300.0], np.float32)
        ctx = rng.randn(2, 8, cfg.context_dim).astype(np.float32)
        y = rng.randn(2, cfg.adm_in_channels).astype(np.float32)
        hint = rng.rand(2, 64, 64, 3).astype(np.float32)

        with torch.no_grad():
            ref = tmodel(_nchw(x), torch.from_numpy(t), torch.from_numpy(ctx),
                         torch.from_numpy(y), _nchw(hint))
        down, mid = bundle.apply(jnp.asarray(x), jnp.asarray(t),
                                 jnp.asarray(ctx), jnp.asarray(y),
                                 jnp.asarray(hint))
        assert len(down) == len(ref) - 1
        for ours, theirs in zip(down + [mid], ref):
            np.testing.assert_allclose(np.asarray(ours), _nhwc(theirs),
                                       atol=3e-4, rtol=3e-4)

    def test_unconsumed_key_fails(self, pair):
        cfg, _, bundle, sd = pair
        bad = dict(sd)
        bad["control_model.extra"] = np.zeros(1, np.float32)
        tmpl = init_controlnet(cfg, jax.random.key(0),
                               sample_shape=(8, 8, 4), context_len=8).params
        with pytest.raises(ConversionError, match="unconsumed"):
            convert_controlnet(bad, tmpl, cfg)


class TestUNetHook:
    def test_zero_init_control_is_identity(self):
        """Random-init ControlNet has zero-init output convs → residuals
        are exactly zero → the UNet output is bit-identical (the cldm
        training-start property; proves the hook wiring adds nothing)."""
        from comfyui_distributed_tpu.models.unet import init_unet

        cfg = UNetConfig.tiny(dtype="float32")
        model, params = init_unet(cfg, jax.random.key(0),
                                  sample_shape=(8, 8, 4), context_len=8)
        cn = init_controlnet(cfg, jax.random.key(1), sample_shape=(8, 8, 4),
                             context_len=8)
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(1, 8, 8, 4), jnp.float32)
        t = jnp.array([10.0], jnp.float32)
        ctx = jnp.asarray(rng.randn(1, 8, cfg.context_dim), jnp.float32)
        y = jnp.asarray(rng.randn(1, cfg.adm_in_channels), jnp.float32)
        hint = jnp.asarray(rng.rand(1, 64, 64, 3), jnp.float32)

        control = cn.apply(x, t, ctx, y, hint)
        plain = model.apply(params, x, t, ctx, y)
        hooked = model.apply(params, x, t, ctx, y, control=control)
        np.testing.assert_array_equal(np.asarray(plain), np.asarray(hooked))


class TestPipeline:
    def test_controlled_generation_differs_and_caches(self, tmp_config):
        from comfyui_distributed_tpu.diffusion.pipeline import GenerationSpec
        from comfyui_distributed_tpu.parallel import build_mesh

        bundle = ModelRegistry().get("tiny")
        cfg = bundle.preset.unet
        cn = init_controlnet(cfg, jax.random.key(3), sample_shape=(8, 8, 4),
                             context_len=bundle.preset.text.max_len)
        # make residuals non-zero (trained-checkpoint stand-in)
        cn.params = jax.tree_util.tree_map(
            lambda a: a + 0.03 if a.ndim >= 1 else a, cn.params)
        mesh = build_mesh({"dp": len(jax.devices())})
        ctx, _ = bundle.text_encoder.encode(["p"])
        unc, _ = bundle.text_encoder.encode([""])
        spec = GenerationSpec(height=16, width=16, steps=2,
                              guidance_scale=1.0, per_device_batch=1)
        hint = jnp.zeros((1, 64, 64, 3), jnp.float32)

        plain = np.asarray(bundle.pipeline.generate(mesh, spec, 5, ctx, unc))
        controlled_pipe = bundle.pipeline.with_control(cn, strength=1.0)
        controlled = np.asarray(
            controlled_pipe.generate(mesh, spec, 5, ctx, unc, hint=hint))
        assert controlled.shape == plain.shape
        assert not np.allclose(controlled, plain)
        # clone memoized; base pipeline untouched
        assert bundle.pipeline.with_control(cn, 1.0) is controlled_pipe
        assert getattr(bundle.pipeline, "_control", None) is None

    def test_missing_hint_fails(self, tmp_config):
        from comfyui_distributed_tpu.diffusion.pipeline import GenerationSpec
        from comfyui_distributed_tpu.parallel import build_mesh

        bundle = ModelRegistry().get("tiny")
        cn = init_controlnet(bundle.preset.unet, jax.random.key(0),
                             sample_shape=(8, 8, 4),
                             context_len=bundle.preset.text.max_len)
        pipe = bundle.pipeline.with_control(cn)
        ctx, _ = bundle.text_encoder.encode(["p"])
        mesh = build_mesh({"dp": 1})
        with pytest.raises(ValueError, match="hint"):
            pipe.generate(mesh, GenerationSpec(height=16, width=16, steps=1),
                          0, ctx, ctx)


def _f32_controlled_stack(strength=1.0):
    """float32 tiny pipeline + ControlNet (invariance must be asserted in
    f32 — bf16 legitimately varies ~1e-2 with batch shape; see
    tests/test_tiles.py::test_upscale_shard_count_independent)."""
    from comfyui_distributed_tpu.diffusion.pipeline import Txt2ImgPipeline
    from comfyui_distributed_tpu.models.text import (TextEncoder,
                                                     TextEncoderConfig)
    from comfyui_distributed_tpu.models.unet import init_unet
    from comfyui_distributed_tpu.models.vae import AutoencoderKL, VAEConfig

    cfg = UNetConfig.tiny(dtype="float32")
    model, params = init_unet(cfg, jax.random.key(0),
                              sample_shape=(8, 8, 4), context_len=16)
    vae = AutoencoderKL(VAEConfig.tiny(dtype="float32")).init(
        jax.random.key(1), image_hw=(16, 16))
    pipe = Txt2ImgPipeline(model, params, vae)
    cfg_f32 = UNetConfig.tiny(dtype="float32")
    cn = init_controlnet(cfg_f32, jax.random.key(3),
                         sample_shape=(8, 8, 4), context_len=16)
    cn.params = jax.tree_util.tree_map(
        lambda a: a + 0.02 if a.ndim >= 1 else a, cn.params)
    enc = TextEncoder(TextEncoderConfig.tiny()).init(jax.random.key(2))
    ctx, _ = enc.encode(["p"])
    unc, _ = enc.encode([""])
    return pipe, pipe.with_control(cn, strength=strength), ctx, unc


class TestTileEngine:
    def test_per_tile_hint_crop_single_tile(self, tmp_config):
        """1-tile grid with a control hint: shard-count invariant in f32,
        and control visibly changes the output — the engine's analogue of
        the reference's per-tile ControlNet crop (usdu_utils.py:506)."""
        from comfyui_distributed_tpu.parallel import build_mesh
        from comfyui_distributed_tpu.tiles.engine import (TileUpscaler,
                                                          UpscaleSpec)

        plain_pipe, ctrl_pipe, ctx, unc = _f32_controlled_stack()
        img = jax.random.uniform(jax.random.key(0), (1, 16, 16, 3))
        hint = jax.random.uniform(jax.random.key(1), (1, 128, 128, 3))
        spec = UpscaleSpec(scale=2.0, tile_w=32, tile_h=32, padding=4,
                           steps=2, denoise=0.4, guidance_scale=1.0)

        ups = TileUpscaler(ctrl_pipe)
        m1 = build_mesh({"dp": 1})
        m8 = build_mesh({"dp": len(jax.devices())})
        a = np.asarray(ups.upscale(m1, img, spec, 7, ctx, unc,
                                   control_hint=hint))
        b = np.asarray(ups.upscale(m8, img, spec, 7, ctx, unc,
                                   control_hint=hint))
        assert a.shape == (1, 32, 32, 3)
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)

        # control changes the tiles (vs the same run without a hint)
        plain = np.asarray(TileUpscaler(plain_pipe).upscale(
            m1, img, spec, 7, ctx, unc))
        assert not np.allclose(a, plain)

    def test_multi_tile_control_shard_invariant(self, tmp_config):
        from comfyui_distributed_tpu.parallel import build_mesh
        from comfyui_distributed_tpu.tiles.engine import (TileUpscaler,
                                                          UpscaleSpec)

        _, ctrl_pipe, ctx, unc = _f32_controlled_stack(strength=0.8)
        img = jax.random.uniform(jax.random.key(2), (1, 16, 16, 3))
        hint = jax.random.uniform(jax.random.key(3), (1, 64, 64, 3))
        # 2×2 grid at output res 32
        spec = UpscaleSpec(scale=2.0, tile_w=16, tile_h=16, padding=4,
                           steps=2, denoise=0.4, guidance_scale=1.0)
        ups = TileUpscaler(ctrl_pipe)
        m1 = build_mesh({"dp": 1})
        m8 = build_mesh({"dp": len(jax.devices())})
        a = np.asarray(ups.upscale(m1, img, spec, 9, ctx, unc,
                                   control_hint=hint))
        b = np.asarray(ups.upscale(m8, img, spec, 9, ctx, unc,
                                   control_hint=hint))
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


class TestNodes:
    def test_loader_apply_and_sample(self, tmp_config):
        from comfyui_distributed_tpu.graph.node import get_node
        from comfyui_distributed_tpu.graph import nodes_builtin

        nodes_builtin._controlnet_cache.clear()
        (cn,) = get_node("ControlNetLoader")().execute("tiny")
        assert cn.name == "tiny"
        (again,) = get_node("ControlNetLoader")().execute("tiny")
        assert again is cn

        bundle = ModelRegistry().get("tiny")
        ctx, _ = bundle.text_encoder.encode(["p"])
        cond = {"context": ctx}
        hint_img = np.random.RandomState(0).rand(1, 16, 16, 3).astype("f4")
        (ccond,) = get_node("ControlNetApply")().execute(cond, cn, hint_img,
                                                         strength=0.7)
        assert ccond["control"]["strength"] == 0.7
        assert "context" in ccond

        (out,) = get_node("TPUTxt2Img")().execute(
            bundle, ccond, {"context": ctx}, seed=1, steps=2, cfg=1.0,
            width=16, height=16)
        assert np.asarray(out).shape == (len(jax.devices()), 16, 16, 3)
        nodes_builtin._controlnet_cache.clear()

    def test_loader_unknown_fails(self, tmp_config):
        from comfyui_distributed_tpu.graph.node import get_node
        from comfyui_distributed_tpu.utils.exceptions import ValidationError

        with pytest.raises(ValidationError):
            get_node("ControlNetLoader")().execute("nope")
