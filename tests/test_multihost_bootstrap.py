"""REAL two-process ``jax.distributed`` bring-up (VERDICT r3 next #6).

Every other multi-host test injects ``initialize_fn``; this one runs the
genuine article: a coordinator + 2 OS processes on the CPU backend (gloo
collectives), ``init_multihost`` resolving everything from the CDT_* env
vars — the exact path ``serve`` takes on a pod (``docs/deployment.md``
§2) — then asserts global membership and one cross-host psum.
"""

import os
import socket
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow      # spawns two fresh JAX processes

REPO = str(Path(__file__).resolve().parent.parent)

CHILD = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    # an accelerator sitecustomize (e.g. the axon tunnel plugin) may have
    # set jax_platforms programmatically, which overrides the env var and
    # silently breaks CPU multi-process membership — force cpu the same
    # way tests/conftest.py does
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    sys.path.insert(0, os.environ["CDT_REPO"])
    from comfyui_distributed_tpu.parallel.bootstrap import init_multihost
    from comfyui_distributed_tpu.utils.jax_compat import shard_map

    # no initialize_fn injection: the real jax.distributed.initialize,
    # config entirely from CDT_COORDINATOR/CDT_NUM_HOSTS/CDT_HOST_INDEX
    assert init_multihost() is True

    import numpy as np
    import jax.numpy as jnp

    assert jax.process_count() == 2, jax.process_count()
    assert jax.local_device_count() == 2
    assert len(jax.devices()) == 4, jax.devices()   # GLOBAL device list

    from comfyui_distributed_tpu.parallel import build_mesh

    mesh = build_mesh({"dp": 4})                    # spans both processes
    from jax.sharding import NamedSharding, PartitionSpec as P

    # cross-host psum: each device contributes (process_index+1); the sum
    # 2*(0+1) + 2*(1+1) = 6 is only reachable if the collective crossed
    # the process boundary
    contrib = jnp.full((jax.local_device_count(), 1),
                       float(jax.process_index() + 1))
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), np.asarray(contrib), (4, 1))

    @jax.jit
    def total(x):
        return shard_map(
            lambda s: jax.lax.psum(s, "dp"),
            mesh=mesh, in_specs=P("dp"), out_specs=P(),
        )(x)

    out = np.asarray(jax.device_get(
        [s.data for s in total(garr).addressable_shards][0]))
    assert out.ravel()[0] == 6.0, out
    print("MULTIHOST_OK", jax.process_index(), flush=True)
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_bringup(tmp_path):
    port = _free_port()
    script = tmp_path / "child.py"
    script.write_text(CHILD)
    procs = []
    for idx in range(2):
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("JAX_", "XLA_"))}
        # drop accelerator-plugin site dirs (sitecustomize there would
        # pre-register a tunneled backend in the child)
        if "PYTHONPATH" in env:
            parts = [p for p in env["PYTHONPATH"].split(os.pathsep)
                     if "axon" not in p]
            if parts:
                env["PYTHONPATH"] = os.pathsep.join(parts)
            else:
                env.pop("PYTHONPATH")
        env.update({
            "CDT_REPO": REPO,
            "CDT_COORDINATOR": f"127.0.0.1:{port}",
            "CDT_NUM_HOSTS": "2",
            "CDT_HOST_INDEX": str(idx),
            # each child compiles a trivial program; isolate caches so a
            # cross-flag AOT mismatch can't SIGILL (memory: axon env)
            "JAX_COMPILATION_CACHE_DIR": str(tmp_path / f"xla{idx}"),
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for idx, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"host {idx} failed:\n{out[-3000:]}"
        assert f"MULTIHOST_OK {idx}" in out
