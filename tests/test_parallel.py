"""Mesh / sharding / RNG / collective tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from comfyui_distributed_tpu.utils.jax_compat import shard_map
from comfyui_distributed_tpu.parallel import (
    MeshSpec,
    build_mesh,
    device_census,
    mesh_from_config,
    participant_key,
    participant_keys,
    seed_to_key,
    shard_batch,
)
from comfyui_distributed_tpu.parallel import collectives, mesh as mesh_mod
from comfyui_distributed_tpu.parallel.rng import participant_seeds
from comfyui_distributed_tpu.utils.exceptions import ShardingError

pytestmark = pytest.mark.slow  # compile-heavy: builds/jits real model stacks


def test_device_census_virtual_8():
    census = device_census()
    assert len(census) == 8
    assert all(d["platform"] == "cpu" for d in census)


def test_mesh_spec_resolution():
    assert MeshSpec.from_mapping({"dp": -1}).resolve(8) == (8,)
    assert MeshSpec.from_mapping({"dp": -1, "tp": 2}).resolve(8) == (4, 2)
    assert MeshSpec.from_mapping({"dp": 2, "tp": 2}).resolve(8) == (2, 2)
    assert MeshSpec.from_mapping({"dp": 3}).resolve(8) == (3,)  # subset mesh
    with pytest.raises(ShardingError):
        MeshSpec.from_mapping({"dp": -1, "tp": -1})


def test_mesh_spec_subset_and_indivisible():
    # fixed axes may use a subset of devices
    m = build_mesh({"dp": 3})
    assert m.shape == {"dp": 3}
    # -1 with indivisible fixed product fails
    with pytest.raises(ShardingError):
        MeshSpec.from_mapping({"dp": -1, "tp": 3}).resolve(8)
    with pytest.raises(ShardingError):
        MeshSpec.from_mapping({"dp": 16}).resolve(8)


def test_build_mesh_and_describe():
    m = build_mesh({"dp": 4, "tp": 2})
    assert m.axis_names == ("dp", "tp")
    d = mesh_mod.describe_mesh(m)
    assert d["axes"] == {"dp": 4, "tp": 2}
    assert d["n_devices"] == 8


def test_mesh_from_config_default():
    m = mesh_from_config({})
    assert m.shape == {"dp": 8}


def test_shard_batch_placement():
    m = build_mesh({"dp": 8})
    x = jnp.arange(16.0).reshape(16, 1)
    sx = shard_batch(m, x)
    assert sx.sharding.spec == P("dp", None)
    np.testing.assert_allclose(np.asarray(sx), np.asarray(x))


def test_participant_keys_match_in_and_out_of_mesh():
    """Host-side participant_keys must equal what participant_key yields at
    each mesh index — the contract that makes single-host replay of a
    sharded run deterministic."""
    m = build_mesh({"dp": 8})
    base = seed_to_key(42)

    def inner(_):
        k = participant_key(base, "dp")
        return jax.random.bits(k, (1, 4))

    f = shard_map(
        inner, mesh=m, in_specs=(P("dp", None),), out_specs=P("dp", None)
    )
    sharded_bits = f(jnp.zeros((8, 1)))
    host_keys = participant_keys(base, 8)
    host_bits = jax.vmap(lambda k: jax.random.bits(k, (4,)))(host_keys)
    np.testing.assert_array_equal(np.asarray(sharded_bits), np.asarray(host_bits))
    # all participants draw distinct streams
    assert len({tuple(r) for r in np.asarray(host_bits)}) == 8


def test_participant_seeds_reference_parity():
    # master keeps seed; worker N gets seed+N+1 (nodes/utilities.py:52-75)
    assert participant_seeds(100, 4) == [100, 101, 102, 103]


def test_gather_batch_order():
    """gather_batch concatenates shards in mesh-index order (master-first
    contract of the reference collector)."""
    m = build_mesh({"dp": 8})

    def inner(x):
        i = collectives.shard_index("dp")
        return collectives.gather_batch(x + i.astype(x.dtype))

    f = jax.jit(
        shard_map(
            inner, mesh=m, in_specs=(P("dp", None),), out_specs=P(None, None),
            check_vma=False,
        )
    )
    out = f(jnp.zeros((8, 2)))
    np.testing.assert_array_equal(
        np.asarray(out[:, 0]), np.arange(8, dtype=np.float32)
    )


def test_ring_shift():
    m = build_mesh({"dp": 8})

    def inner(x):
        i = collectives.shard_index("dp").astype(x.dtype)
        shifted = collectives.ring_shift(x + i, "dp", shift=1)
        return shifted

    f = jax.jit(shard_map(inner, mesh=m, in_specs=(P("dp", None),), out_specs=P("dp", None)))
    out = np.asarray(f(jnp.zeros((8, 1))))
    # shard i holds value of shard i-1 (ring)
    expected = (np.arange(8) - 1) % 8
    np.testing.assert_array_equal(out[:, 0], expected)


class TestMultihostBootstrap:
    """Bootstrap logic with a faked jax.distributed.initialize (the real
    one needs a live coordinator; the code path is identical)."""

    def _reset(self):
        from comfyui_distributed_tpu.parallel import bootstrap
        bootstrap._initialized = False
        return bootstrap

    def test_noop_without_coordinator(self, monkeypatch):
        b = self._reset()
        monkeypatch.delenv("CDT_COORDINATOR", raising=False)
        calls = []
        assert b.init_multihost(initialize_fn=lambda **kw: calls.append(kw)) is False
        assert calls == []

    def test_explicit_args_forwarded(self):
        b = self._reset()
        calls = []
        ok = b.init_multihost("10.0.0.1:9911", 4, 2,
                              initialize_fn=lambda **kw: calls.append(kw))
        assert ok is True
        assert calls == [{"coordinator_address": "10.0.0.1:9911",
                          "num_processes": 4, "process_id": 2}]
        # idempotent: second call doesn't re-initialize
        assert b.init_multihost("10.0.0.1:9911", 4, 2,
                                initialize_fn=lambda **kw: calls.append(kw))
        assert len(calls) == 1

    def test_env_fallbacks(self, monkeypatch):
        b = self._reset()
        monkeypatch.setenv("CDT_COORDINATOR", "c:1")
        monkeypatch.setenv("CDT_NUM_HOSTS", "2")
        monkeypatch.setenv("CDT_HOST_INDEX", "1")
        calls = []
        assert b.init_multihost(initialize_fn=lambda **kw: calls.append(kw))
        assert calls[0]["num_processes"] == 2 and calls[0]["process_id"] == 1

    def test_incomplete_config_raises(self, monkeypatch):
        b = self._reset()
        monkeypatch.delenv("CDT_NUM_HOSTS", raising=False)
        monkeypatch.delenv("CDT_HOST_INDEX", raising=False)
        with pytest.raises(ValueError):
            b.init_multihost("c:1", initialize_fn=lambda **kw: None)
        with pytest.raises(ValueError):
            b.init_multihost("c:1", 4, 7, initialize_fn=lambda **kw: None)
