"""Elastic fleet subsystem (cluster/elastic, ISSUE 10): drain states,
deterministic cross-job stealing, the autoscaler policy loop, graceful
drain/decommission, and the chaos-marked scale-event acceptance run.
"""

import asyncio

import numpy as np
import pytest

from comfyui_distributed_tpu.cluster.elastic.autoscaler import (
    AutoscalePolicy, Autoscaler, FleetSignals)
from comfyui_distributed_tpu.cluster.elastic.drain import DrainCoordinator
from comfyui_distributed_tpu.cluster.elastic.scheduler import (
    JobView, StealPolicy)
from comfyui_distributed_tpu.cluster.elastic.states import (
    ACTIVE, DECOMMISSIONED, DRAIN, DRAINING, DrainRegistry)
from comfyui_distributed_tpu.cluster.job_store import JobStore
from comfyui_distributed_tpu.cluster.resilience import BREAKERS


def make_proc(value_scale=1.5, delay=0.0):
    """Deterministic on the GLOBAL tile index (same discipline as the
    chaos tests): any host computing tile i produces identical pixels,
    so steal/handback/requeue are provably invisible in the output."""
    import time as _t

    def proc(start, end):
        if delay:
            _t.sleep(delay)
        return np.stack([np.full((4, 4, 3), float(i) * value_scale + 0.25,
                                 np.float32)
                         for i in range(start, end)])
    return proc


# ---------------------------------------------------------------------------
# lifecycle registry
# ---------------------------------------------------------------------------


class TestDrainRegistry:
    def test_unknown_workers_are_active(self):
        reg = DrainRegistry()
        assert reg.state("nobody") == ACTIVE
        assert not reg.is_leaving("nobody")

    def test_forward_transitions_and_reactivate(self):
        reg = DrainRegistry(clock=lambda: 100.0)
        assert reg.mark_draining("w0", deadline_s=5.0)
        assert reg.state("w0") == DRAINING
        assert reg.is_leaving("w0") and reg.is_draining("w0")
        assert reg.deadline("w0") == 105.0
        reg.mark_decommissioned("w0")
        assert reg.state("w0") == DECOMMISSIONED
        assert reg.is_leaving("w0") and not reg.is_draining("w0")
        assert reg.reactivate("w0")
        assert reg.state("w0") == ACTIVE

    def test_double_drain_is_idempotent(self):
        """A second drain request must not reset the deadline clock."""
        now = [0.0]
        reg = DrainRegistry(clock=lambda: now[0])
        assert reg.mark_draining("w0", deadline_s=10.0)
        now[0] = 5.0
        assert not reg.mark_draining("w0", deadline_s=10.0)
        assert reg.deadline("w0") == 10.0   # the ORIGINAL deadline

    def test_reset_clears_everything(self):
        reg = DrainRegistry()
        reg.mark_draining("a")
        reg.mark_decommissioned("b")
        reg.reset()
        assert reg.states() == {}
        assert reg.state("a") == ACTIVE


# ---------------------------------------------------------------------------
# steal scheduler policy
# ---------------------------------------------------------------------------


class TestStealPolicy:
    VIEWS = [
        JobView("jobA", seq=1, pending=10, active_workers=2),
        JobView("jobB", seq=2, pending=3, active_workers=0),
        JobView("jobC", seq=3, pending=8, active_workers=0),
        JobView("done", seq=4, pending=0, active_workers=1),
    ]

    def test_most_starved_first(self):
        """Fewest workers wins; deeper pending breaks the worker tie;
        drained jobs never granted."""
        ranked = StealPolicy(seed=0).rank(self.VIEWS, "w0")
        assert [v.job_id for v in ranked] == ["jobC", "jobB", "jobA"]

    def test_deterministic_under_seed(self):
        a = StealPolicy(seed=7).rank(self.VIEWS, "w0")
        b = StealPolicy(seed=7).rank(self.VIEWS, "w0")
        assert [v.job_id for v in a] == [v.job_id for v in b]

    def test_exact_ties_settled_by_seeded_hash(self):
        views = [JobView("x", seq=1, pending=5, active_workers=0),
                 JobView("y", seq=2, pending=5, active_workers=0)]
        picks = {StealPolicy(seed=s).pick(views, "w0").job_id
                 for s in range(16)}
        # both orders occur across seeds, each seed is stable
        assert picks == {"x", "y"}
        for s in range(4):
            assert (StealPolicy(seed=s).pick(views, "w0").job_id
                    == StealPolicy(seed=s).pick(views, "w0").job_id)

    def test_empty_when_nothing_pending(self):
        assert StealPolicy().pick(
            [JobView("j", seq=1, pending=0, active_workers=0)], "w") is None


class TestJobStoreSteal:
    def test_any_work_grants_across_jobs_with_job_id(self):
        async def body():
            store = JobStore()
            await store.init_tile_job("a", 2)
            await store.init_tile_job("b", 3)
            seen = {"a": 0, "b": 0}
            for _ in range(5):
                task = await store.request_any_work("w0",
                                                    policy=StealPolicy(seed=1))
                assert task is not None and task["job_id"] in seen
                seen[task["job_id"]] += 1
            assert seen == {"a": 2, "b": 3}
            assert await store.request_any_work("w0") is None
        asyncio.run(body())

    def test_any_work_prefers_the_starved_job(self):
        """Job a has a worker on it; job b has none — the first "*"
        grant to a second worker must come from b."""
        async def body():
            store = JobStore()
            await store.init_tile_job("a", 4)
            await store.init_tile_job("b", 4)
            assert (await store.request_work("a", "w0")) is not None
            task = await store.request_any_work("w1",
                                                policy=StealPolicy(seed=0))
            assert task["job_id"] == "b"
        asyncio.run(body())


# ---------------------------------------------------------------------------
# drain handback accounting (leaving ≠ broken)
# ---------------------------------------------------------------------------


class TestHandback:
    def test_handback_requeues_without_poison_count(self):
        async def body():
            store = JobStore()
            await store.init_tile_job("j", 4)
            t0 = await store.request_work("j", "w0")
            t1 = await store.request_work("j", "w0")
            handed = await store.handback_worker_tasks("w0")
            assert handed == {"j": [t0["task_id"], t1["task_id"]]}
            job = store.tile_jobs["j"]
            # back at the FRONT, exactly once, and NOT counted
            assert [t.task_id for t in job.pending][:2] == \
                sorted([t0["task_id"], t1["task_id"]])
            assert len(job.pending) == 4
            assert job.requeue_counts == {}
            assert job.assigned == {}
            # idempotent: a second handback finds nothing
            assert await store.handback_worker_tasks("w0") == {}
        asyncio.run(body())

    def test_handback_never_dead_letters(self, monkeypatch):
        """Even a task already at the poison bound goes back to the
        queue on an intentional departure — only FAILURES count."""
        from comfyui_distributed_tpu.utils import constants

        monkeypatch.setattr(constants, "MAX_TILE_REQUEUES", 1)

        async def body():
            store = JobStore()
            await store.init_tile_job("j", 1)
            task = await store.request_work("j", "w0")
            store.tile_jobs["j"].requeue_counts[task["task_id"]] = 1
            handed = await store.handback_worker_tasks("w0")
            assert handed == {"j": [task["task_id"]]}
            assert store.tile_jobs["j"].dead_letter == {}
            assert store.tile_jobs["j"].requeue_counts == \
                {task["task_id"]: 1}   # untouched
        asyncio.run(body())

    def test_eviction_of_draining_worker_spares_breaker_once(self):
        """The heartbeat monitor finding a silent DRAINING worker hands
        its tiles back (no breaker trip, no requeue count) — and the
        later coordinator handback finds nothing (exactly-once)."""
        from comfyui_distributed_tpu.cluster.job_timeout import (
            check_and_requeue_timed_out_workers)

        async def body():
            store = JobStore()
            await store.init_tile_job("j", 3)
            await store.request_work("j", "w0")
            await store.request_work("j", "w0")
            DRAIN.mark_draining("w0")
            evicted = await check_and_requeue_timed_out_workers(
                store, "j", timeout=0.0, now=1e9)
            assert sorted(evicted["w0"]) == [0, 1]
            assert BREAKERS.state("w0") == "closed"   # never tripped
            job = store.tile_jobs["j"]
            assert job.requeue_counts == {}
            assert len(job.pending) == 3
            # the drain coordinator's own handback double-checks: empty
            assert await store.handback_worker_tasks("w0") == {}
            assert len(store.tile_jobs["j"].pending) == 3
        asyncio.run(body())

    def test_eviction_of_failed_worker_still_trips_breaker(self):
        """Control case: a NON-draining silent worker keeps the PR 3
        behavior — breaker trips, requeues count."""
        from comfyui_distributed_tpu.cluster.job_timeout import (
            check_and_requeue_timed_out_workers)

        async def body():
            store = JobStore()
            await store.init_tile_job("j", 2)
            await store.request_work("j", "w1")
            evicted = await check_and_requeue_timed_out_workers(
                store, "j", timeout=0.0, now=1e9)
            assert evicted["w1"] == [0]
            assert BREAKERS.state("w1") == "open"
            assert store.tile_jobs["j"].requeue_counts == {0: 1}
        asyncio.run(body())


class TestHealthyFraction:
    def test_draining_workers_leave_the_denominator(self):
        from comfyui_distributed_tpu.cluster.frontdoor.admission import (
            breaker_healthy_fraction)

        BREAKERS.record("w0", True)
        BREAKERS.trip("w1")
        assert breaker_healthy_fraction() == 0.5
        # w1 is not broken — it was told to leave: full health again
        DRAIN.mark_draining("w1")
        assert breaker_healthy_fraction() == 1.0
        # an all-leaving tracked set reads as a fresh fleet, not a dead one
        DRAIN.mark_draining("w0")
        assert breaker_healthy_fraction() == 1.0


# ---------------------------------------------------------------------------
# autoscaler policy loop
# ---------------------------------------------------------------------------


class FakeProvider:
    def __init__(self, launchable=("w1", "w2", "w3")):
        self.pool = list(launchable)
        self.running: dict[str, str] = {}
        self.drained: list[str] = []

    def list_workers(self):
        return {w: {"state": s, "running": True}
                for w, s in self.running.items()}

    def scale_up(self):
        if not self.pool:
            return None
        wid = self.pool.pop(0)
        self.running[wid] = "active"
        return wid

    def scale_down(self, worker_id):
        self.running[worker_id] = "draining"
        self.drained.append(worker_id)


def make_scaler(signals_seq, provider=None, policy=None, t0=1000.0):
    now = {"t": t0}
    sig_iter = iter(signals_seq)
    last = {"s": None}

    def signals():
        try:
            last["s"] = next(sig_iter)
        except StopIteration:
            pass
        return last["s"]

    scaler = Autoscaler(signals, provider or FakeProvider(),
                        policy=policy, clock=lambda: now["t"])
    return scaler, now


class TestAutoscaler:
    POLICY = AutoscalePolicy(min_workers=0, max_workers=2,
                             scale_up_depth=4.0, scale_down_depth=0.5,
                             up_streak=2, down_streak=2,
                             up_cooldown_s=10.0, down_cooldown_s=10.0)

    def test_hysteresis_one_hot_tick_holds(self):
        provider = FakeProvider()
        scaler, now = make_scaler(
            [FleetSignals(20, 0, active_workers=0),
             FleetSignals(0, 0, active_workers=0)],
            provider, self.POLICY)
        assert scaler.evaluate().direction == "hold"   # streak 1 < 2
        assert scaler.evaluate().direction == "hold"   # pressure gone
        assert provider.running == {}

    def test_sustained_pressure_scales_up_then_cooldown(self):
        provider = FakeProvider()
        sig = FleetSignals(20, 4, active_workers=0)
        scaler, now = make_scaler([sig] * 10, provider, self.POLICY)
        assert scaler.evaluate().direction == "hold"
        d = scaler.evaluate()
        assert (d.direction, d.worker_id) == ("up", "w1")
        # still pressured, but the cooldown gates the next launch
        assert scaler.evaluate().direction == "hold"
        now["t"] += 11.0
        d2 = scaler.evaluate()   # streak rebuilt during cooldown ticks
        assert (d2.direction, d2.worker_id) == ("up", "w2")

    def test_envelope_max_blocks(self):
        provider = FakeProvider()
        provider.running = {"w1": "active", "w2": "active"}
        scaler, _ = make_scaler(
            [FleetSignals(50, 0, active_workers=2)] * 3,
            provider, self.POLICY)
        scaler.evaluate()
        assert scaler.evaluate().reason == "envelope_max"

    def test_idle_fleet_drains_one_deterministically(self):
        provider = FakeProvider()
        provider.running = {"w1": "active", "w2": "active"}
        scaler, _ = make_scaler(
            [FleetSignals(0, 0, active_workers=2)] * 3,
            provider, self.POLICY)
        scaler.evaluate()
        d = scaler.evaluate()
        # scale-down is a DRAIN of the lexicographically-last active
        assert (d.direction, d.worker_id) == ("down", "w2")
        assert provider.drained == ["w2"]
        assert provider.running["w2"] == "draining"

    def test_envelope_min_blocks_drain(self):
        pol = AutoscalePolicy(min_workers=1, max_workers=2,
                              scale_up_depth=4.0, scale_down_depth=0.5,
                              up_streak=2, down_streak=2,
                              up_cooldown_s=0.0, down_cooldown_s=0.0)
        provider = FakeProvider()
        provider.running = {"w1": "active"}
        scaler, _ = make_scaler(
            [FleetSignals(0, 0, active_workers=1)] * 3, provider, pol)
        scaler.evaluate()
        assert scaler.evaluate().reason == "envelope_min"
        assert provider.drained == []

    def test_no_capacity_reported(self):
        provider = FakeProvider(launchable=())
        scaler, _ = make_scaler(
            [FleetSignals(50, 0, active_workers=0)] * 3,
            provider, self.POLICY)
        scaler.evaluate()
        assert scaler.evaluate().reason == "no_capacity"

    def test_status_shape(self):
        scaler, _ = make_scaler(
            [FleetSignals(2, 1, active_workers=1)], FakeProvider(),
            self.POLICY)
        scaler.evaluate()
        st = scaler.status()
        assert st["pressure"] == 1.5
        assert st["policy"]["max_workers"] == 2
        assert st["recent_decisions"]


class TestStepTimeSignal:
    def test_step_time_p50_reads_merged_histogram(self):
        """The autoscaler's latency context comes from the shared
        cdt_sampler_step_seconds family (merged across pipelines)."""
        from comfyui_distributed_tpu.cluster.elastic import _step_time_p50
        from comfyui_distributed_tpu.telemetry import metrics as _tm

        for _ in range(64):   # dominate whatever earlier tests observed
            _tm.SAMPLER_STEP_SECONDS.labels(pipeline="txt2img").observe(0.05)
        p50 = _step_time_p50()
        assert p50 is not None and 0.0 < p50 <= 1.0


# ---------------------------------------------------------------------------
# drain coordinator
# ---------------------------------------------------------------------------


class TestDrainCoordinator:
    def test_clean_drain_waits_for_inflight_then_decommissions(self):
        async def body():
            store = JobStore()
            await store.init_tile_job("j", 2)
            task = await store.request_work("j", "w0")
            stopped = []
            coord = DrainCoordinator(store, poll_interval=0.02,
                                     process_stopper=lambda w:
                                     stopped.append(w) or True)
            report = coord.begin("w0", deadline_s=5.0)
            assert report["phase"] == "draining"
            assert DRAIN.is_draining("w0")
            # the worker finishes its held task → drain completes clean
            await asyncio.sleep(0.05)
            await store.submit_result("j", "w0", task["task_id"],
                                      {"image": np.zeros((1, 4, 4, 3))})
            final = await coord.wait("w0")
            assert final["phase"] == "decommissioned"
            assert final["handed_back"] == {}
            assert stopped == ["w0"]
            assert DRAIN.state("w0") == DECOMMISSIONED
        asyncio.run(body())

    def test_deadline_handback_returns_held_work(self):
        async def body():
            store = JobStore()
            await store.init_tile_job("j", 3)
            t = await store.request_work("j", "w0")
            coord = DrainCoordinator(store, poll_interval=0.02,
                                     process_stopper=None)
            coord.begin("w0", deadline_s=0.1)
            final = await coord.wait("w0")
            assert final["phase"] == "decommissioned"
            assert final["handed_back"] == {"j": [t["task_id"]]}
            assert len(store.tile_jobs["j"].pending) == 3
            assert store.tile_jobs["j"].requeue_counts == {}
        asyncio.run(body())

    def test_undrain_cancels_and_reactivates(self):
        async def body():
            store = JobStore()
            await store.init_tile_job("j", 2)
            await store.request_work("j", "w0")
            coord = DrainCoordinator(store, poll_interval=0.02)
            coord.begin("w0", deadline_s=30.0)
            await asyncio.sleep(0.05)
            assert coord.undrain("w0")
            await asyncio.sleep(0.05)
            assert DRAIN.state("w0") == ACTIVE
            # held work was NOT handed back — the worker is staying
            assert store.tile_jobs["j"].assigned == {0: "w0"}
            # the cancelled drain task must not clobber the verdict
            # on its later CancelledError tick
            assert coord.reports["w0"]["phase"] == "reactivated"
        asyncio.run(body())

    def test_begin_is_idempotent_while_draining(self):
        async def body():
            store = JobStore()
            coord = DrainCoordinator(store, poll_interval=0.02)
            r1 = coord.begin("w0", deadline_s=30.0)
            r2 = coord.begin("w0", deadline_s=1.0)   # ignored
            assert r1["deadline_s"] == r2["deadline_s"] == 30.0
            coord.undrain("w0")
        asyncio.run(body())


# ---------------------------------------------------------------------------
# HTTP surface + probe integration
# ---------------------------------------------------------------------------


def _serve_master():
    from aiohttp.test_utils import TestClient, TestServer

    from comfyui_distributed_tpu.api.app import create_app
    from comfyui_distributed_tpu.cluster.controller import Controller

    controller = Controller()
    return controller, TestClient(TestServer(create_app(controller)))


class TestDrainRoutes:
    def test_drain_route_stops_grants_and_probes(self, tmp_config):
        async def body():
            controller, client = _serve_master()
            async with client:
                store = controller.store
                await store.init_tile_job("j", 4)
                # pre-drain: w0 gets work
                resp = await client.post(
                    "/distributed/request_image",
                    json={"job_id": "*", "worker_id": "w0"})
                body0 = await resp.json()
                assert body0["task"]["job_id"] == "j"

                resp = await client.post(
                    "/distributed/worker/w0/drain",
                    json={"deadline_s": 0.2, "stop_process": False})
                assert resp.status == 200
                assert (await resp.json())["status"] == "draining"

                # a draining worker is REFUSED work, explicitly
                resp = await client.post(
                    "/distributed/request_image",
                    json={"job_id": "*", "worker_id": "w0"})
                body1 = await resp.json()
                assert body1 == {"task": None, "draining": True}

                # probe fan-out skips it without probing or breaker harm
                from comfyui_distributed_tpu.cluster.dispatch import (
                    select_active_hosts)

                online, offline = await select_active_hosts(
                    [{"id": "w0", "host": "127.0.0.1", "port": 1}])
                assert online == []
                assert offline[0]["_drain"] == DRAINING
                assert BREAKERS.state("w0") == "closed"

                # deadline passes → handback + decommission, visible on
                # the status surface
                await controller.elastic.coordinator.wait("w0")
                resp = await client.get("/distributed/elastic")
                st = await resp.json()
                assert st["drain"]["states"]["w0"] == DECOMMISSIONED
                report = st["drain"]["reports"]["w0"]
                assert report["handed_back"] == {"j": [0]}
                assert len(store.tile_jobs["j"].pending) == 4

                # undrain re-admits
                resp = await client.post("/distributed/worker/w0/undrain",
                                         json={})
                assert (await resp.json())["cleared"] is True
                resp = await client.post(
                    "/distributed/request_image",
                    json={"job_id": "*", "worker_id": "w0"})
                assert (await resp.json())["task"] is not None
        asyncio.run(body())

    def test_drain_route_validates_deadline(self, tmp_config):
        async def body():
            _, client = _serve_master()
            async with client:
                resp = await client.post(
                    "/distributed/worker/w0/drain",
                    json={"deadline_s": "soon"})
                assert resp.status == 400
                resp = await client.post(
                    "/distributed/worker/w0/drain",
                    json={"deadline_s": -1})
                assert resp.status == 400
        asyncio.run(body())

    def test_local_worker_status_carries_drain_state(self, tmp_config):
        async def body():
            controller, client = _serve_master()
            async with client:
                DRAIN.mark_draining("w7")
                from comfyui_distributed_tpu.utils.config import (
                    load_config, update_config)

                update_config(lambda c: c.update(hosts=[
                    {"id": "w7", "type": "local", "host": "127.0.0.1",
                     "port": 1, "enabled": True}]))
                resp = await client.get("/distributed/local-worker-status")
                workers = (await resp.json())["workers"]
                assert workers["w7"]["drain"] == DRAINING
        asyncio.run(body())


class TestStealWorkerLoop:
    def test_steal_loop_serves_both_jobs_and_hands_back_unknown(
            self, tmp_config):
        from comfyui_distributed_tpu.cluster.tile_farm import (
            TileFarm, assemble_tiles)

        async def body():
            controller, client = _serve_master()
            async with client:
                base = f"http://127.0.0.1:{client.port}"
                farm = controller.tile_farm
                proc_a, proc_b = make_proc(1.5), make_proc(-2.0)
                mA = asyncio.create_task(farm.master_run_async(
                    "jobA", total=6, process_fn=make_proc(1.5, delay=0.2),
                    chunk=1, heartbeat_interval=0.2))
                mB = asyncio.create_task(farm.master_run_async(
                    "jobB", total=6, process_fn=make_proc(-2.0, delay=0.2),
                    chunk=1, heartbeat_interval=0.2))
                await asyncio.sleep(0.05)

                worker_farm = TileFarm(JobStore(),
                                       asyncio.get_running_loop())
                resolve = {"jobA": proc_a, "jobB": proc_b}.get
                done = await worker_farm.worker_steal_run_async(
                    "w0", base, resolve, idle_polls=2, idle_interval=0.1)
                resA, resB = await asyncio.gather(mA, mB)
                # the one steal worker served BOTH jobs
                assert set(done) == {"jobA", "jobB"}
                assert sum(done.values()) > 0
                outA = assemble_tiles(resA, 6, 1)
                outB = assemble_tiles(resB, 6, 1)
                np.testing.assert_array_equal(outA, np.concatenate(
                    [proc_a(i, i + 1) for i in range(6)]))
                np.testing.assert_array_equal(outB, np.concatenate(
                    [proc_b(i, i + 1) for i in range(6)]))
        asyncio.run(body())

    def test_unservable_grant_is_handed_back(self, tmp_config):
        from comfyui_distributed_tpu.cluster.tile_farm import TileFarm

        async def body():
            controller, client = _serve_master()
            async with client:
                base = f"http://127.0.0.1:{client.port}"
                store = controller.store
                await store.init_tile_job("alien", 2)
                worker_farm = TileFarm(JobStore(),
                                       asyncio.get_running_loop())
                done = await worker_farm.worker_steal_run_async(
                    "w0", base, lambda jid: None,
                    idle_polls=1, idle_interval=0.05)
                assert done == {}
                # the grant went back to the queue, uncounted
                job = store.tile_jobs["alien"]
                assert len(job.pending) == 2
                assert job.assigned == {}
                assert job.requeue_counts == {}
        asyncio.run(body())


    def test_unservable_job_does_not_starve_servable_ones(self,
                                                          tmp_config):
        """Regression: the worker sends its can't-serve list with every
        "*" pull, so a top-ranked unservable job can't ping-pong its
        grant and starve the servable jobs ranked below it."""
        from comfyui_distributed_tpu.cluster.tile_farm import TileFarm

        async def body():
            controller, client = _serve_master()
            async with client:
                base = f"http://127.0.0.1:{client.port}"
                store = controller.store
                # A ranks first (deepest pending, no workers) but the
                # worker lacks its weights; B must still fully drain
                await store.init_tile_job("A", 8)
                await store.init_tile_job("B", 3)
                worker_farm = TileFarm(JobStore(),
                                       asyncio.get_running_loop())
                resolve = {"B": make_proc(2.0)}.get
                done = await worker_farm.worker_steal_run_async(
                    "w0", base, resolve, idle_polls=2, idle_interval=0.05)
                assert done == {"B": 3}
                assert len(store.tile_jobs["B"].completed) == 3
                # A untouched: its grant was handed back, uncounted
                job_a = store.tile_jobs["A"]
                assert len(job_a.pending) == 8
                assert job_a.assigned == {} and job_a.requeue_counts == {}
        asyncio.run(body())

    def test_steal_loop_heartbeats_every_buffered_job(self, tmp_config,
                                                      monkeypatch):
        """Regression: a steal worker holding UNFLUSHED results for job A
        while the scheduler has it grinding job B must keep heartbeating
        A — or A's monitor would falsely evict it through the failure
        path with its results sitting in the buffer."""
        from comfyui_distributed_tpu.cluster.tile_farm import TileFarm

        beats: list[str] = []

        async def spy_heartbeat(self, session, base, job_id, worker_id):
            beats.append(job_id)

        monkeypatch.setattr(TileFarm, "_heartbeat", spy_heartbeat)

        async def body():
            controller, client = _serve_master()
            async with client:
                base = f"http://127.0.0.1:{client.port}"
                store = controller.store
                # A has ONE task; B has several — after A's single grant
                # the loop works B while A's result stays buffered
                # (max_batch high enough that nothing flushes mid-run)
                await store.init_tile_job("A", 1)
                await store.init_tile_job("B", 4)
                worker_farm = TileFarm(JobStore(),
                                       asyncio.get_running_loop())
                resolve = {"A": make_proc(1.0), "B": make_proc(2.0)}.get
                done = await worker_farm.worker_steal_run_async(
                    "w0", base, resolve, max_batch=100,
                    idle_polls=1, idle_interval=0.05)
                assert done == {"A": 1, "B": 4}
                # every post-A tick heartbeated A as well as B
                a_beats = beats.count("A")
                assert a_beats >= 4, beats
        asyncio.run(body())

    def test_drain_breaks_steal_loop_immediately(self, tmp_config):
        """Regression: the master marking a steal worker draining must
        end its pull loop NOW (flushing buffered work), not after the
        idle-poll budget — with the budget below set to minutes, a
        prompt exit is only possible via the draining signal."""
        from comfyui_distributed_tpu.cluster.tile_farm import TileFarm

        async def body():
            controller, client = _serve_master()
            async with client:
                base = f"http://127.0.0.1:{client.port}"
                store = controller.store
                await store.init_tile_job("j", 50)

                def proc(start, end):
                    # drain w0 from inside its second tile: the NEXT
                    # pull must come back "draining" and end the loop
                    if start == 1:
                        DRAIN.mark_draining("w0")
                    return make_proc(1.0)(start, end)

                worker_farm = TileFarm(JobStore(),
                                       asyncio.get_running_loop())
                t0 = asyncio.get_event_loop().time()
                done = await asyncio.wait_for(
                    worker_farm.worker_steal_run_async(
                        "w0", base, lambda jid: proc, max_batch=100,
                        idle_polls=100, idle_interval=2.0),
                    timeout=30)
                elapsed = asyncio.get_event_loop().time() - t0
                # exited promptly (not 100 × 2 s of idle polling), and
                # the buffered results were flushed on the way out
                assert elapsed < 10
                assert done == {"j": 2}
                assert len(store.tile_jobs["j"].completed) == 2
        asyncio.run(body())


# ---------------------------------------------------------------------------
# chaos acceptance: a full scale event, bit-identical
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestChaosScaleEvent:
    """ISSUE 10 acceptance: a 3-worker mixed two-job run that scales up
    to 4 (the new worker steals pending tiles from the open jobs), drains
    one worker mid-job (deadline handback), and rolling-restarts another
    (drain → undrain → rejoin under the same id) completes with zero
    admitted-job loss, bit-identical outputs vs the static-fleet run,
    zero dead-letters, and NO breaker ever opening — every departure in
    this run is intentional."""

    TOTALS = {"sdxl": 30, "usdu": 20}

    def _reference(self):
        from comfyui_distributed_tpu.cluster.tile_farm import (
            TileFarm, assemble_tiles)

        async def body():
            out = {}
            for jid, total in self.TOTALS.items():
                farm = TileFarm(JobStore(), asyncio.get_running_loop())
                res = await farm.master_run_async(
                    f"ref-{jid}", total=total,
                    process_fn=self._proc(jid), chunk=1,
                    heartbeat_interval=0.2)
                out[jid] = assemble_tiles(res, total, 1)
            return out
        return asyncio.run(body())

    @staticmethod
    def _proc(jid, delay=0.0):
        return make_proc(1.5 if jid == "sdxl" else -2.0, delay=delay)

    def test_scale_event_is_lossless_and_bit_identical(self, tmp_config):
        from comfyui_distributed_tpu.cluster.tile_farm import (
            TileFarm, assemble_tiles)

        ref = self._reference()

        async def chaotic():
            controller, client = _serve_master()
            async with client:
                base = f"http://127.0.0.1:{client.port}"
                loop = asyncio.get_running_loop()
                # workers pay a small per-tile cost so the run is still
                # mid-flight when the scale events land (values depend
                # only on the global index — delay can't change bits)
                resolve = {"sdxl": self._proc("sdxl", delay=0.05),
                           "usdu": self._proc("usdu", delay=0.05)}.get

                def steal_worker(wid):
                    farm = TileFarm(JobStore(), loop)
                    return farm.worker_steal_run_async(
                        wid, base, resolve, idle_polls=3,
                        idle_interval=0.1)

                # the master grinds slowly so the fleet does real work;
                # worker_timeout is generous — NOTHING in this run may
                # leave via the failure path
                masters = [asyncio.create_task(
                    controller.tile_farm.master_run_async(
                        jid, total=total,
                        process_fn=self._proc(jid, delay=0.2), chunk=1,
                        heartbeat_interval=0.2, worker_timeout=30.0))
                    for jid, total in self.TOTALS.items()]
                await asyncio.sleep(0.05)   # jobs seeded

                # --- the 3-worker fleet: w1 and w2 pull work and HOLD it
                async def hold(wid, n):
                    held = []
                    for _ in range(n):
                        async with client.session.post(
                                f"{base}/distributed/request_image",
                                json={"job_id": "*",
                                      "worker_id": wid}) as r:
                            t = (await r.json())["task"]
                            if t:
                                held.append((t["job_id"], t["task_id"]))
                    return held

                held1 = await hold("w1", 2)
                held2 = await hold("w2", 1)
                assert held1 and held2
                w0_task = asyncio.create_task(steal_worker("w0"))

                # --- scale-down: drain w1 while it HOLDS work; the
                # deadline handback returns its tiles to the queue
                async with client.session.post(
                        f"{base}/distributed/worker/w1/drain",
                        json={"deadline_s": 0.2,
                              "stop_process": False}) as r:
                    assert r.status == 200
                await controller.elastic.coordinator.wait("w1")
                handed1 = controller.elastic.coordinator.reports[
                    "w1"]["handed_back"]
                assert sum(map(len, handed1.values())) == len(held1)

                # --- rolling restart, phase 1: drain w2 (its held tile
                # comes back via handback); the restarted generation
                # rejoins AFTER the scale-up below
                async with client.session.post(
                        f"{base}/distributed/worker/w2/drain",
                        json={"deadline_s": 0.2,
                              "stop_process": False}) as r:
                    assert r.status == 200
                await controller.elastic.coordinator.wait("w2")

                # --- scale-up to 4: the AUTOSCALER launches w3 off the
                # real queue-depth signal; the provider's launch starts
                # a steal loop, which immediately picks up pending tiles
                launched: dict[str, asyncio.Task] = {}

                class TestProvider:
                    def list_workers(self):
                        return {w: {"state": DRAIN.state(w),
                                    "running": True} for w in launched}

                    def scale_up(self):
                        wid = f"w{3 + len(launched)}"
                        launched[wid] = asyncio.create_task(
                            steal_worker(wid))
                        return wid

                    def scale_down(self, wid):
                        controller.elastic.coordinator.begin(wid)

                def signals():
                    depth = sum(len(j.pending) for j in
                                controller.store.tile_jobs.values())
                    return FleetSignals(queue_depth=0, tile_depth=depth,
                                        active_workers=len(launched))

                scaler = Autoscaler(
                    signals, TestProvider(),
                    policy=AutoscalePolicy(max_workers=1, up_streak=2,
                                           up_cooldown_s=0.0))
                decisions = [scaler.evaluate() for _ in range(3)]
                assert [d.direction for d in decisions].count("up") == 1
                assert "w3" in launched

                # --- rolling restart, phase 2: w2 rejoins under the
                # same id (undrain) once the new capacity is up
                async with client.session.post(
                        f"{base}/distributed/worker/w2/undrain",
                        json={}) as r:
                    assert (await r.json())["cleared"] is True
                w2_task = asyncio.create_task(steal_worker("w2"))

                results = await asyncio.gather(*masters)
                done3 = await asyncio.wait_for(launched["w3"], timeout=60)
                assert sum(done3.values()) > 0, \
                    "scale-up worker stole nothing"
                await asyncio.gather(w0_task, w2_task)

                # --- acceptance ---------------------------------------
                for (jid, total), res in zip(self.TOTALS.items(), results):
                    out = assemble_tiles(res, total, 1)
                    np.testing.assert_array_equal(out, ref[jid])
                for jid in self.TOTALS:
                    async with client.session.get(
                            f"{base}/distributed/job_status",
                            params={"job_id": jid}) as r:
                        status = await r.json()
                    assert status["finished"] is True
                    assert status["dead_letter"] == []
                    assert status["completed"] == self.TOTALS[jid]
                # no breaker ever opened: every departure was intentional
                assert all(s == "closed"
                           for s in BREAKERS.states().values()), \
                    BREAKERS.states()
                assert DRAIN.state("w1") == DECOMMISSIONED
                assert DRAIN.state("w2") == ACTIVE
        asyncio.run(chaotic())
