"""Sampling progress + live previews: events stream out of the compiled
sampler scan (jax.debug.callback), the tracker aggregates them, and the
control plane serves them — the standalone equivalent of the per-step
progress/preview UX the reference inherits from ComfyUI."""

import asyncio

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from comfyui_distributed_tpu.cluster.progress import (ProgressTracker,
                                                      latent_to_rgb)
from comfyui_distributed_tpu.diffusion import progress as events
from comfyui_distributed_tpu.diffusion.progress import (calls_per_step,
                                                        total_calls,
                                                        wrap_denoiser)

@pytest.fixture(autouse=True)
def _fresh_sink_registry():
    """Sinks now COEXIST (registry) instead of latest-wins: a Controller
    built by an earlier test file that never closed its tracker would
    otherwise leak into this module's registry-emptiness assertions."""
    events.set_sink(None)          # clears the whole registry
    yield
    events.set_sink(None)


@pytest.fixture
def tracker():
    t = ProgressTracker()
    yield t
    t.close()


class TestLatentToRgb:
    def test_4ch_linear_map(self):
        rgb = latent_to_rgb(np.random.randn(8, 8, 4).astype(np.float32))
        assert rgb.shape == (8, 8, 3)
        assert rgb.min() >= 0.0 and rgb.max() <= 1.0

    def test_16ch_fallback(self):
        rgb = latent_to_rgb(np.random.randn(8, 8, 16).astype(np.float32))
        assert rgb.shape == (8, 8, 3)

    def test_video_latent_takes_middle_frame(self):
        rgb = latent_to_rgb(np.random.randn(5, 8, 8, 4).astype(np.float32))
        assert rgb.shape == (8, 8, 3)


class TestTracker:
    def test_counts_and_preview_ordering(self, tracker):
        token = tracker.start("p1", total_calls("euler", 4))
        lat_hi = np.full((1, 4, 4, 4), 7.0, np.float32)
        lat_lo = np.full((1, 4, 4, 4), 1.0, np.float32)
        # unordered arrival: the low-sigma (later) event first
        tracker._on_event(token, 0, 2.0, lat_lo)
        tracker._on_event(token, 0, 14.0, lat_hi)
        snap = tracker.snapshot("p1")
        assert snap["step"] == 2 and snap["total"] == 4
        assert snap["fraction"] == 0.5
        # preview kept the LOWEST sigma seen (newest step), not the last
        assert tracker._jobs[token].previews[0][0, 0, 0] == 1.0

    def test_shard_previews_kept_separately(self, tracker):
        token = tracker.start("p2", 4)
        tracker._on_event(token, 0, 5.0, np.zeros((1, 4, 4, 4), np.float32))
        tracker._on_event(token, 1, 5.0, np.ones((1, 4, 4, 4), np.float32))
        snap = tracker.snapshot("p2")
        assert snap["shards_reporting"] == 2
        assert snap["step"] == 1            # shard 0 only drives the count

    def test_finish_clamps_and_blocks_late_events(self, tracker):
        token = tracker.start("p3", 10)
        tracker._on_event(token, 0, 5.0, np.zeros((1, 2, 2, 4), np.float32))
        tracker.finish("p3")
        snap = tracker.snapshot("p3")
        assert snap["done"] and snap["fraction"] == 1.0
        tracker._on_event(token, 0, 1.0, np.ones((1, 2, 2, 4), np.float32))
        assert tracker.snapshot("p3")["step"] == 10

    def test_preview_png_roundtrip(self, tracker):
        from comfyui_distributed_tpu.utils.image import decode_png

        token = tracker.start("p4", 2)
        tracker._on_event(token, 0, 3.0,
                          np.random.randn(1, 8, 8, 4).astype(np.float32))
        png = tracker.preview_png("p4")
        assert png is not None
        assert decode_png(png).shape == (8, 8, 3)

    def test_unknown_prompt(self, tracker):
        assert tracker.snapshot("nope") is None
        assert tracker.preview_png("nope") is None

    def test_eviction_keeps_newest(self):
        t = ProgressTracker(keep=2)
        try:
            t.start("a", 1)
            t.start("b", 1)
            t.start("c", 1)
            assert t.snapshot("a") is None
            assert t.snapshot("c") is not None
        finally:
            t.close()


class TestCallsPerStep:
    def test_table(self):
        assert calls_per_step("euler") == 1
        assert calls_per_step("heun") == 2
        assert calls_per_step("dpmpp_sde") == 2
        assert total_calls("euler", 30) == 30

    def test_second_order_total_is_exact_not_upper_bound(self):
        """heun/dpmpp_sde take the single-call Euler fallback on their
        final step (sigma_next == 0), so the exact total is 2n-1 — an
        upper bound of 2n would stall the bar at (2n-1)/2n until
        finish() clamps it."""
        assert total_calls("heun", 30) == 59
        assert total_calls("dpmpp_sde", 30) == 59
        assert total_calls("heun", 1) == 1

    def test_second_order_event_count_matches_total(self):
        """Count actual wrapped-denoiser events through a jitted heun run
        and check they land exactly on total_calls."""
        from comfyui_distributed_tpu.diffusion import sample, sigmas_karras

        seen = []
        handle = events.add_sink(lambda tok, sh, sig, x0: seen.append(sig))
        try:
            steps = 5
            sigmas = sigmas_karras(steps, 0.03, 10.0)
            den = wrap_denoiser(lambda x, s: x * 0.5, jnp.int32(1),
                                jnp.int32(0))
            out = sample("heun", den, jnp.ones((1, 4, 4, 1)), sigmas)
            jax.block_until_ready(out)
            jax.effects_barrier()
            assert len(seen) == total_calls("heun", steps) == 2 * steps - 1
        finally:
            events.remove_sink(handle)


class TestTrackerCoexistence:
    """VERDICT r3 weak #4: two trackers in one process (embedded
    master+worker, back-to-back Controllers in tests) must BOTH keep
    receiving their own events — no stealing, no RuntimeWarning."""

    def test_two_trackers_route_independently(self):
        import warnings as _w

        t1 = ProgressTracker()
        try:
            with _w.catch_warnings():
                _w.simplefilter("error")        # any warning = failure
                t2 = ProgressTracker()
            try:
                tok1 = t1.start("p1", 4)
                tok2 = t2.start("p2", 4)
                assert tok1 != tok2             # global token allocator
                lat = np.zeros((1, 2, 2, 4), np.float32)
                # fan-out: dispatch through the module-level path, as the
                # compiled program would
                events._dispatch(tok1, 0, 1.0, lat)
                events._dispatch(tok2, 0, 1.0, lat)
                assert t1.snapshot("p1")["step"] == 1
                assert t2.snapshot("p2")["step"] == 1
                # neither tracker saw the other's token
                assert t1.snapshot("p2") is None
                assert t2.snapshot("p1") is None
            finally:
                t2.close()
        finally:
            t1.close()

    def test_close_detaches_only_own_sink(self):
        t1 = ProgressTracker()
        t1.close()
        assert events.get_sink() is None
        t1.close()  # idempotent
        t2 = ProgressTracker()
        t3 = ProgressTracker()
        t2.close()  # must NOT detach t3
        assert events.get_sink() is not None
        token = t3.start("p3", 2)
        events._dispatch(token, 0, 1.0, np.zeros((1, 2, 2, 4), np.float32))
        assert t3.snapshot("p3")["step"] == 1
        t3.close()
        assert events.get_sink() is None


def test_wrapped_denoiser_streams_through_jit(tracker):
    """The wrapper emits one event per model call from inside a jitted
    scan, with the traced token routed at runtime."""
    token = tracker.start("jit1", 3)
    den = wrap_denoiser(lambda x, s: x * 0.5, jnp.int32(token), 0)

    def scan_fn(x, sigma):
        return den(x, sigma), None

    xs = jnp.array([3.0, 2.0, 1.0])
    jax.block_until_ready(
        jax.jit(lambda x0: jax.lax.scan(scan_fn, x0, xs))(
            jnp.ones((1, 4, 4, 4))))
    # callbacks are async host effects — drain them before asserting
    jax.effects_barrier()
    snap = tracker.snapshot("jit1")
    assert snap["step"] == 3
    assert snap["fraction"] == 1.0


@pytest.mark.slow  # builds a real model stack
def test_pipeline_generate_with_progress(tracker, tmp_config):
    """End-to-end: dp-sharded tiny generation with a progress token — the
    tracker sees every step and a preview from each shard."""
    from comfyui_distributed_tpu.diffusion.pipeline import (GenerationSpec,
                                                            Txt2ImgPipeline)
    from comfyui_distributed_tpu.models.text import (TextEncoder,
                                                     TextEncoderConfig)
    from comfyui_distributed_tpu.models.unet import UNetConfig, init_unet
    from comfyui_distributed_tpu.models.vae import AutoencoderKL, VAEConfig
    from comfyui_distributed_tpu.parallel import build_mesh

    model, params = init_unet(UNetConfig.tiny(), jax.random.key(0),
                              sample_shape=(8, 8, 4), context_len=16)
    vae = AutoencoderKL(VAEConfig.tiny()).init(jax.random.key(1),
                                               image_hw=(16, 16))
    enc = TextEncoder(TextEncoderConfig.tiny()).init(jax.random.key(2))
    pipe = Txt2ImgPipeline(model, params, vae)
    ctx, _ = enc.encode(["progress"])
    unc, _ = enc.encode([""])
    mesh = build_mesh({"dp": 4})
    spec = GenerationSpec(height=16, width=16, steps=3, guidance_scale=2.0)

    token = tracker.start("run1", total_calls(spec.sampler, spec.steps))
    out = pipe.generate(mesh, spec, 0, ctx, unc, progress_token=token)
    jax.block_until_ready(out)
    jax.effects_barrier()       # block_until_ready does not flush callbacks
    snap = tracker.snapshot("run1")
    assert snap["step"] == 3, snap
    assert snap["shards_reporting"] == 4
    assert tracker.preview_png("run1", shard=3) is not None
    # progress-off compiles separately and still works (cache keyed)
    out2 = pipe.generate(mesh, spec, 0, ctx, unc)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2),
                               rtol=1e-5, atol=1e-5)


def test_progress_routes(tmp_config):
    """Route surface: /distributed/progress + /preview."""
    from aiohttp.test_utils import TestClient, TestServer

    from comfyui_distributed_tpu.api.app import create_app
    from comfyui_distributed_tpu.cluster.controller import Controller

    async def body():
        controller = Controller()
        app = create_app(controller)
        token = controller.progress.start("pr1", 4)
        controller.progress._on_event(
            token, 0, 3.0, np.random.randn(1, 8, 8, 4).astype(np.float32))
        async with TestClient(TestServer(app)) as client:
            r = await client.get("/distributed/progress/pr1")
            assert r.status == 200
            data = await r.json()
            assert data["step"] == 1 and data["total"] == 4
            r = await client.get("/distributed/preview/pr1")
            assert r.status == 200
            assert r.content_type == "image/png"
            r = await client.get("/distributed/progress/none")
            assert r.status == 404
        controller.progress.close()

    asyncio.run(body())


@pytest.mark.slow  # builds a real model stack
def test_flow_pipeline_progress(tracker, tmp_config):
    """FLUX-path progress: the flow pipeline streams steps too, and its
    compiled-fn cache keys progress separately."""
    from comfyui_distributed_tpu.diffusion.pipeline_flow import (FlowPipeline,
                                                                 FlowSpec)
    from comfyui_distributed_tpu.models.dit import DiTConfig, init_dit
    from comfyui_distributed_tpu.models.vae import AutoencoderKL, VAEConfig
    from comfyui_distributed_tpu.parallel import build_mesh

    cfg = DiTConfig.tiny()
    model, params = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                             context_len=6)
    vae = AutoencoderKL(VAEConfig.tiny()).init(jax.random.key(1),
                                               image_hw=(16, 16))
    pipe = FlowPipeline(model, params, vae)
    ctx = jnp.zeros((1, 6, cfg.context_dim))
    pooled = jnp.zeros((1, cfg.pooled_dim))
    mesh = build_mesh({"dp": 2})
    spec = FlowSpec(height=16, width=16, steps=3)

    token = tracker.start("flow1", total_calls(spec.sampler, spec.steps))
    out = pipe.generate(mesh, spec, 0, ctx, pooled, progress_token=token)
    jax.block_until_ready(out)
    jax.effects_barrier()
    snap = tracker.snapshot("flow1")
    assert snap["step"] == 3, snap
    assert snap["shards_reporting"] == 2
    # cache: same (mesh, spec) with progress off is a separate entry that
    # still runs
    out2 = pipe.generate(mesh, spec, 0, ctx, pooled)
    assert np.asarray(out2).shape == np.asarray(out).shape
    assert len(pipe._fn_cache) == 2


@pytest.mark.slow  # builds a real video model stack
def test_video_pipeline_progress(tracker):
    """VERDICT r2 weak #4: t2v jobs (the longest-running) were opaque.
    The dp video path now streams per-step events and the preview route
    renders a FRAME STRIP for video latents."""
    from comfyui_distributed_tpu.diffusion.pipeline_video import (
        VideoPipeline, VideoSpec)
    from comfyui_distributed_tpu.models.vae import AutoencoderKL, VAEConfig
    from comfyui_distributed_tpu.models.video_dit import (VideoDiTConfig,
                                                          init_video_dit)
    from comfyui_distributed_tpu.parallel import build_mesh

    cfg = VideoDiTConfig(patch_size=2, in_channels=4, hidden=64,
                         depth_double=1, depth_single=1, heads=4,
                         context_dim=32, pooled_dim=16, dtype="float32")
    model, params = init_video_dit(cfg, jax.random.key(0),
                                   sample_fhw=(4, 8, 8), context_len=6)
    vae = AutoencoderKL(VAEConfig.tiny(dtype="float32")).init(
        jax.random.key(1), image_hw=(16, 16))
    pipe = VideoPipeline(model, params, vae)
    ctx = jnp.ones((1, 6, cfg.context_dim)) * 0.1
    pooled = jnp.ones((1, cfg.pooled_dim)) * 0.2

    mesh = build_mesh({"dp": 2})
    spec = VideoSpec(frames=5, height=16, width=16, steps=3, shift=1.0)
    token = tracker.start("vid1", spec.steps)
    vids = pipe.generate(mesh, spec, 0, ctx, pooled, progress_token=token)
    jax.block_until_ready(vids)
    jax.effects_barrier()
    snap = tracker.snapshot("vid1")
    assert snap["step"] == 3 and snap["fraction"] == 1.0
    assert snap["shards_reporting"] == 2
    # the stored preview is a VIDEO latent → strip of frames, wider than
    # a single-frame render
    from comfyui_distributed_tpu.utils.image import decode_png

    png = tracker.preview_png("vid1")
    strip = decode_png(png)
    assert strip.shape[1] > strip.shape[0]      # 4 frames side by side
    tracker.finish("vid1")


class TestVideoStrip:
    def test_strip_tiles_up_to_four_frames(self, tracker):
        token = tracker.start("v2", 2)
        lat = np.random.randn(1, 6, 8, 8, 4).astype(np.float32)  # video x0
        tracker._on_event(token, 0, 5.0, lat)
        from comfyui_distributed_tpu.utils.image import decode_png

        strip = decode_png(tracker.preview_png("v2"))
        assert strip.shape == (8, 32, 3)        # 4 evenly-spaced frames

    def test_short_video_uses_all_frames(self, tracker):
        token = tracker.start("v3", 2)
        lat = np.random.randn(1, 2, 8, 8, 4).astype(np.float32)
        tracker._on_event(token, 0, 5.0, lat)
        from comfyui_distributed_tpu.utils.image import decode_png

        strip = decode_png(tracker.preview_png("v3"))
        assert strip.shape == (8, 16, 3)        # both frames
