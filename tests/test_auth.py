"""Control-plane auth: optional shared-secret token gating mutating routes.

The reference exposes an unauthenticated control plane through public
tunnels (``/root/reference/utils/cloudflare/tunnel.py``); this framework
closes that with a cluster token (``utils/auth.py``): mutating routes 401
without it, probes/health stay open, outbound peer calls attach it
automatically, and starting a tunnel auto-generates one.
"""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from comfyui_distributed_tpu.api import create_app
from comfyui_distributed_tpu.cluster.controller import Controller
from comfyui_distributed_tpu.utils import auth
from comfyui_distributed_tpu.utils.config import load_config, update_config


def run(coro):
    return asyncio.run(coro)


def make_client():
    controller = Controller()
    app = create_app(controller)
    return controller, TestClient(TestServer(app))


class TestPolicy:
    def test_gets_open_posts_gated(self):
        assert not auth.requires_auth("GET", "/distributed/health")
        assert not auth.requires_auth("GET", "/distributed/progress/p1")
        assert not auth.requires_auth("OPTIONS", "/distributed/queue")
        assert auth.requires_auth("POST", "/distributed/queue")
        assert auth.requires_auth("POST", "/distributed/launch_worker")
        assert auth.requires_auth("POST", "/upload/image")
        # the one gated read: the config payload contains the token
        assert auth.requires_auth("GET", "/distributed/config")

    def test_env_wins_over_config(self, monkeypatch):
        monkeypatch.setenv(auth.AUTH_ENV, "env-tok")
        cfg = {"settings": {"auth_token": "cfg-tok"}}
        assert auth.configured_token(cfg) == "env-tok"
        monkeypatch.delenv(auth.AUTH_ENV)
        assert auth.configured_token(cfg) == "cfg-tok"
        assert auth.configured_token({"settings": {}}) is None
        assert auth.configured_token(None) is None

    def test_token_matches_header_and_bearer(self):
        assert auth.token_matches({"X-CDT-Auth": "t1"}, "t1")
        assert auth.token_matches({"Authorization": "Bearer t1"}, "t1")
        assert not auth.token_matches({"X-CDT-Auth": "nope"}, "t1")
        assert not auth.token_matches({}, "t1")
        assert not auth.token_matches({"Authorization": "Basic t1"}, "t1")

    def test_non_ascii_header_is_401_not_500(self):
        """hmac.compare_digest raises TypeError on non-ASCII *strings*;
        a malformed credential must read as a mismatch, not a crash."""
        assert not auth.token_matches({"X-CDT-Auth": "tokén"}, "token")

    def test_log_reads_gated(self):
        """Log surfaces can carry secrets (and the buffer once carried the
        generated token) — they are gated reads when auth is on."""
        assert auth.requires_auth("GET", "/distributed/local_log")
        assert auth.requires_auth("GET", "/distributed/worker_log/w0")
        assert auth.requires_auth("GET", "/distributed/remote_worker_log/w0")
        assert not auth.requires_auth("GET", "/distributed/health")


class TestRoutes:
    def _enable(self, token="secret-token"):
        def mutate(cfg):
            cfg.setdefault("settings", {})["auth_token"] = token
        update_config(mutate)

    def test_mutating_401_without_token(self, tmp_config):
        self._enable()

        async def body():
            controller, client = make_client()
            async with client:
                resp = await client.post("/prompt", json={"prompt": {
                    "1": {"class_type": "PrimitiveInt",
                          "inputs": {"value": 1}}}})
                assert resp.status == 401
                resp = await client.post("/distributed/queue",
                                         json={"prompt": {"1": {}}})
                assert resp.status == 401
                resp = await client.get("/distributed/config")
                assert resp.status == 401
        run(body())

    def test_mutating_200_with_header_or_bearer(self, tmp_config):
        self._enable()

        async def body():
            controller, client = make_client()
            async with client:
                resp = await client.post(
                    "/prompt",
                    json={"prompt": {"1": {"class_type": "PrimitiveInt",
                                           "inputs": {"value": 1}}}},
                    headers={"X-CDT-Auth": "secret-token"})
                assert resp.status == 200
                resp = await client.get(
                    "/distributed/config",
                    headers={"Authorization": "Bearer secret-token"})
                assert resp.status == 200
        run(body())

    def test_probes_and_reads_stay_open(self, tmp_config):
        self._enable()

        async def body():
            controller, client = make_client()
            async with client:
                for path in ("/distributed/health", "/prompt",
                             "/distributed/system_info"):
                    resp = await client.get(path)
                    assert resp.status == 200, path
        run(body())

    def test_no_token_configured_everything_open(self, tmp_config):
        async def body():
            controller, client = make_client()
            async with client:
                resp = await client.post("/prompt", json={"prompt": {
                    "1": {"class_type": "PrimitiveInt",
                          "inputs": {"value": 1}}}})
                assert resp.status == 200
        run(body())

    def test_env_token_gates_without_config(self, tmp_config, monkeypatch):
        monkeypatch.setenv(auth.AUTH_ENV, "env-tok")

        async def body():
            controller, client = make_client()
            async with client:
                resp = await client.post("/distributed/clear_memory", json={})
                assert resp.status == 401
                resp = await client.post("/distributed/clear_memory", json={},
                                         headers={"X-CDT-Auth": "env-tok"})
                assert resp.status == 200
        run(body())


class TestOutboundSession:
    def test_session_carries_token_and_rotates(self, tmp_config, monkeypatch):
        from comfyui_distributed_tpu.utils import network

        async def body():
            monkeypatch.setenv(auth.AUTH_ENV, "tok-a")
            s1 = network.get_client_session()
            assert s1.headers.get(auth.AUTH_HEADER) == "tok-a"
            # same token → same session object (no churn)
            assert network.get_client_session() is s1
            # rotation → fresh session with the new header; the OLD
            # session is retired but NOT closed (in-flight requests on it
            # must complete)
            monkeypatch.setenv(auth.AUTH_ENV, "tok-b")
            s2 = network.get_client_session()
            assert s2 is not s1
            assert s2.headers.get(auth.AUTH_HEADER) == "tok-b"
            assert not s1.closed
            monkeypatch.delenv(auth.AUTH_ENV)
            s3 = network.get_client_session()
            assert auth.AUTH_HEADER not in s3.headers
            # close drains current AND retired sessions
            await network.close_client_session()
            assert s1.closed and s2.closed and s3.closed
        run(body())

    def test_two_controller_roundtrip_with_auth(self, tmp_config, monkeypatch):
        """Master→worker dispatch keeps working when BOTH sides share a
        token: the pooled session attaches it to every outbound call."""
        monkeypatch.setenv(auth.AUTH_ENV, "cluster-tok")

        async def body():
            from comfyui_distributed_tpu.utils import network

            worker_ctl, worker_client = make_client()
            async with worker_client:
                addr = (f"http://{worker_client.server.host}:"
                        f"{worker_client.server.port}")
                session = network.get_client_session()
                async with session.post(
                        f"{addr}/prompt",
                        json={"prompt": {"1": {"class_type": "PrimitiveInt",
                                               "inputs": {"value": 2}}}},
                ) as resp:
                    assert resp.status == 200
            await network.close_client_session()
        run(body())


class TestTunnelTokenGeneration:
    def test_tunnel_start_generates_and_persists_once(self, tmp_config):
        from comfyui_distributed_tpu.utils.tunnel import TunnelManager

        mgr = TunnelManager()
        mgr._ensure_auth_token()
        tok = load_config().get("settings", {}).get("auth_token")
        assert tok and len(tok) >= 24
        mgr._ensure_auth_token()          # idempotent
        assert load_config()["settings"]["auth_token"] == tok

    def test_existing_token_untouched(self, tmp_config):
        from comfyui_distributed_tpu.utils.tunnel import TunnelManager

        def mutate(cfg):
            cfg.setdefault("settings", {})["auth_token"] = "keep-me"
        update_config(mutate)
        TunnelManager()._ensure_auth_token()
        assert load_config()["settings"]["auth_token"] == "keep-me"

    def test_token_never_enters_log_buffer(self, tmp_config):
        """The rolling log buffer is served by /distributed/local_log and
        proxied cross-host; the generated secret must not appear there."""
        from comfyui_distributed_tpu.utils.logging import get_log_buffer
        from comfyui_distributed_tpu.utils.tunnel import TunnelManager

        TunnelManager()._ensure_auth_token()
        token = load_config()["settings"]["auth_token"]
        assert token
        assert all(token not in line for line in get_log_buffer())


class TestPeekSetting:
    def test_peek_tracks_updates_without_deepcopy(self, tmp_config):
        from comfyui_distributed_tpu.utils.config import peek_setting

        assert peek_setting("auth_token") is None
        update_config(lambda c: c.setdefault("settings", {})
                      .__setitem__("auth_token", "fresh"))
        assert peek_setting("auth_token") == "fresh"
        assert peek_setting("debug") is False   # defaults merged
