"""Host-offloaded FLUX execution (diffusion/offload.py): block streaming
must be numerically invisible — the offloaded forward equals DiT.apply,
the python euler ladder equals the scan sampler, and the end-to-end
offloaded generate equals the dp pipeline on one device."""

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np
import pytest

from comfyui_distributed_tpu.diffusion.offload import (
    OffloadedFlux,
    materialize_host_params,
    offload_enabled,
    resident_budget_bytes,
    sample_euler_py,
    tree_bytes,
)
from comfyui_distributed_tpu.models.dit import DiTConfig, init_dit

pytestmark = pytest.mark.slow  # compile-heavy: builds/jits real model stacks


def _stack(pos_embed="rope"):
    cfg = DiTConfig.tiny(pos_embed=pos_embed)
    model, params = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                             context_len=6)
    x = jax.random.normal(jax.random.key(1), (2, 8, 8, cfg.in_channels))
    t = jnp.array([0.7, 0.3])
    ctx = jax.random.normal(jax.random.key(2), (2, 6, cfg.context_dim))
    pooled = jax.random.normal(jax.random.key(3), (2, cfg.pooled_dim))
    return cfg, model, params, x, t, ctx, pooled


class TestFlatBlocks:
    """r04: streamed blocks are flattened to one contiguous buffer per
    dtype (one device_put per block instead of ~20 — per-leaf RTT
    dominated the tunneled stream). The layout must round-trip exactly."""

    def test_roundtrip_uniform_dtype(self):
        from comfyui_distributed_tpu.diffusion.offload import (
            _flatten_block, _unflatten_block)

        blk = {"attn": {"kernel": np.arange(12, dtype=np.float32)
                        .reshape(3, 4),
                        "bias": np.ones(4, np.float32)},
               "norm": {"scale": np.full((3,), 2.0, np.float32)}}
        bufs, treedef, metas = _flatten_block(blk)
        assert set(bufs) == {"float32"}
        assert bufs["float32"].shape == (12 + 4 + 3,)
        out = jax.tree_util.tree_map(
            np.asarray, _unflatten_block(
                {k: jnp.asarray(v) for k, v in bufs.items()},
                treedef, metas))
        jax.tree_util.tree_map(np.testing.assert_array_equal, blk, out)

    def test_roundtrip_mixed_dtypes_and_scalars(self):
        from comfyui_distributed_tpu.diffusion.offload import (
            _flatten_block, _unflatten_block)

        blk = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
               "h": jnp.arange(4, dtype=jnp.bfloat16).reshape(2, 2),
               "step": np.int32(7)}                 # scalar leaf
        bufs, treedef, metas = _flatten_block(blk)
        assert set(bufs) == {"float32", "bfloat16", "int32"}
        out = _unflatten_block(
            {k: jnp.asarray(v) for k, v in bufs.items()}, treedef, metas)
        np.testing.assert_array_equal(np.asarray(out["w"]), blk["w"])
        np.testing.assert_array_equal(np.asarray(out["h"]),
                                      np.asarray(blk["h"]))
        assert np.asarray(out["step"]).item() == 7
        assert np.asarray(out["step"]).shape == ()

    def test_unflatten_traces_inside_jit(self):
        """The block programs unflatten in-trace — static offsets must
        trace cleanly and produce the same numbers under jit."""
        from comfyui_distributed_tpu.diffusion.offload import (
            _flatten_block, _unflatten_block)

        blk = {"a": np.random.randn(4, 5).astype(np.float32),
               "b": np.random.randn(5).astype(np.float32)}
        bufs, treedef, metas = _flatten_block(blk)

        @jax.jit
        def apply(bufs, x):
            p = _unflatten_block(bufs, treedef, metas)
            return x @ p["a"] + p["b"]

        x = np.random.randn(2, 4).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(apply(bufs, x)), x @ blk["a"] + blk["b"],
            rtol=1e-6)


class TestForwardEquivalence:
    @pytest.mark.parametrize("pos_embed", ["rope", "sincos"])
    @pytest.mark.parametrize("resident_bytes", [0, 1 << 40])
    def test_matches_monolithic_apply(self, pos_embed, resident_bytes):
        """All-streamed (0) and all-resident (huge — which engages the
        single scanned program, ``off.stacked``) partitions both equal
        the single-program DiT forward under exact ``native`` dtypes."""
        cfg, model, params, x, t, ctx, pooled = _stack(pos_embed)
        g = jnp.array([3.5, 3.5]) if cfg.guidance_embed else None
        want = np.asarray(model.apply(params, x, t, ctx, pooled, g))
        off = OffloadedFlux(model, params, resident_bytes=resident_bytes,
                            stream_dtype="native")
        if resident_bytes:
            assert off.stacked and not off.streamed and not off.resident
        else:
            assert off.streamed and not off.stacked
        got = np.asarray(off.forward(x, t, ctx, pooled, g))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_partial_residency_matches(self):
        """A budget that fits only SOME blocks: prefix resident, suffix
        streamed, same numbers."""
        cfg, model, params, x, t, ctx, pooled = _stack()
        inner = params["params"]
        one_block = tree_bytes(inner["double_0"])
        glue = tree_bytes({k: v for k, v in inner.items()
                           if not k.startswith(("double_", "single_"))})
        off = OffloadedFlux(model, params,
                            resident_bytes=glue + one_block * 2 + 64,
                            stream_dtype="native")
        assert 0 < len(off.resident) < len(off.block_order)
        assert set(off.resident) | set(off.streamed) == set(off.block_order)
        g = jnp.array([3.5, 3.5])
        want = np.asarray(model.apply(params, x, t, ctx, pooled, g))
        np.testing.assert_allclose(
            np.asarray(off.forward(x, t, ctx, pooled, g)), want,
            rtol=2e-5, atol=2e-5)

    def test_host_numpy_params_accepted(self):
        """The real offload scenario: params arrive as host numpy (a
        full-size init can't live on device)."""
        cfg, model, params, x, t, ctx, pooled = _stack()
        host = jax.tree_util.tree_map(np.asarray, params)
        off = OffloadedFlux(model, host, resident_bytes=0,
                            stream_dtype="native")
        g = jnp.array([3.5, 3.5])
        want = np.asarray(model.apply(params, x, t, ctx, pooled, g))
        np.testing.assert_allclose(
            np.asarray(off.forward(x, t, ctx, pooled, g)), want,
            rtol=2e-5, atol=2e-5)


class TestFp8Quantization:
    """r04: fp8(e4m3) weights-only quantization with per-output-channel
    absmax scales — the optimization that makes a 12B FLUX fit RESIDENT
    in one 16 GB chip (zero bytes streamed per step). Mirrors the
    reference ecosystem's standard fp8 low-VRAM FLUX practice."""

    def test_kernel_roundtrip_error_bounded(self):
        from comfyui_distributed_tpu.diffusion.offload import (
            _flatten_block, _unflatten_block)

        rng = np.random.default_rng(0)
        w = (rng.standard_normal((128, 256)) * 0.02).astype(np.float32)
        blk = {"kernel": w}
        bufs, treedef, metas = _flatten_block(blk, quantize=True)
        assert "float8_e4m3fn" in bufs and "scale" in bufs
        assert bufs["scale"].shape == (256,)       # per output channel
        out = np.asarray(jax.jit(
            lambda b: _unflatten_block(b, treedef, metas)["kernel"])(
            {k: jnp.asarray(v) for k, v in bufs.items()}))
        # e4m3 error model: ≤ half-ulp relative (1/16) in the normal
        # range, plus half a subnormal step (2^-10 × column scale)
        # absolute for weights tiny relative to their column absmax
        scale = np.max(np.abs(w), axis=0) / 448.0
        bound = np.abs(w) / 16.0 + (2.0 ** -10) * scale[None, :] + 1e-12
        assert np.all(np.abs(out - w) <= bound)
        rel = np.abs(out - w) / np.maximum(np.abs(w), 1e-8)
        assert float(np.mean(rel)) < 0.03

    def test_small_leaves_stay_exact(self):
        """Biases / norms / qk-scales are not worth quantizing and must
        round-trip bit-exact."""
        from comfyui_distributed_tpu.diffusion.offload import (
            _flatten_block, _unflatten_block)

        blk = {"kernel": np.random.randn(128, 64).astype(np.float32),
               "bias": np.random.randn(64).astype(np.float32),
               "scale1d": np.random.randn(16).astype(np.float32)}
        bufs, treedef, metas = _flatten_block(blk, quantize=True)
        out = jax.tree_util.tree_map(np.asarray, jax.jit(
            lambda b: _unflatten_block(b, treedef, metas))(
            {k: jnp.asarray(v) for k, v in bufs.items()}))
        np.testing.assert_array_equal(out["bias"], blk["bias"])
        np.testing.assert_array_equal(out["scale1d"], blk["scale1d"])
        assert not np.array_equal(out["kernel"], blk["kernel"])  # lossy

    def test_zero_column_safe(self):
        from comfyui_distributed_tpu.diffusion.offload import (
            _flatten_block, _unflatten_block)

        w = np.random.randn(64, 64).astype(np.float32)
        w[:, 7] = 0.0
        bufs, treedef, metas = _flatten_block({"k": w}, quantize=True)
        out = np.asarray(_unflatten_block(
            {k: jnp.asarray(v) for k, v in bufs.items()}, treedef,
            metas)["k"])
        assert np.all(np.isfinite(out))
        np.testing.assert_array_equal(out[:, 7], 0.0)

    def test_quantized_bytes_roughly_halved(self):
        cfg, model, params, *_ = _stack()
        from comfyui_distributed_tpu.diffusion.offload import \
            _flatten_block

        blk = jax.tree_util.tree_map(
            lambda a: np.asarray(a, ml_dtypes.bfloat16)
            if np.asarray(a).dtype == np.float32 else np.asarray(a),
            params["params"]["double_0"])
        full = tree_bytes(blk)
        bufs, _, _ = _flatten_block(blk, quantize=True)
        assert tree_bytes(bufs) < 0.62 * full

    def test_fp8_forward_close_to_exact(self):
        """End-to-end fp8 (fully-resident scan path) vs the monolithic
        bf16 forward on random-normal weights: quantization noise
        averages over the contraction — a few percent relative L2."""
        cfg = DiTConfig.tiny(pos_embed="rope")
        _, abstract = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                               context_len=6, abstract=True)
        from comfyui_distributed_tpu.diffusion.offload import \
            materialize_host_params

        from comfyui_distributed_tpu.models.dit import DiT
        model = DiT(cfg)
        params = materialize_host_params(abstract, seed=3)
        x = jax.random.normal(jax.random.key(1), (1, 8, 8, cfg.in_channels))
        t = jnp.array([0.5])
        ctx = jax.random.normal(jax.random.key(2), (1, 6, cfg.context_dim))
        pooled = jax.random.normal(jax.random.key(3), (1, cfg.pooled_dim))
        g = jnp.array([3.5])
        want = np.asarray(model.apply(params, x, t, ctx, pooled, g),
                          np.float32)
        off = OffloadedFlux(model, params, resident_bytes=1 << 40,
                            stream_dtype="float8_e4m3fn")
        assert off.stacked and not off.streamed
        got = np.asarray(off.forward(x, t, ctx, pooled, g), np.float32)
        rel_l2 = (np.linalg.norm(got - want)
                  / max(np.linalg.norm(want), 1e-9))
        assert rel_l2 < 0.05, rel_l2

    def test_fp8_streaming_loop_matches_fp8_resident(self):
        """Budget-constrained fp8 (per-block streaming loop) must equal
        the fully-resident scan path bit-for-bit: same quantized buffers,
        same block programs."""
        cfg = DiTConfig.tiny(pos_embed="sincos")
        _, abstract = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                               context_len=6, abstract=True)
        from comfyui_distributed_tpu.diffusion.offload import \
            materialize_host_params

        from comfyui_distributed_tpu.models.dit import DiT
        model = DiT(cfg)
        params = materialize_host_params(abstract, seed=4)
        x = jax.random.normal(jax.random.key(1), (1, 8, 8, cfg.in_channels))
        t = jnp.array([0.5])
        ctx = jax.random.normal(jax.random.key(2), (1, 6, cfg.context_dim))
        pooled = jax.random.normal(jax.random.key(3), (1, cfg.pooled_dim))
        g = jnp.array([3.5])
        res = OffloadedFlux(model, params, resident_bytes=1 << 40,
                            stream_dtype="float8_e4m3fn")
        strm = OffloadedFlux(model, params, resident_bytes=0,
                             stream_dtype="float8_e4m3fn")
        assert strm.streamed and not strm.stacked
        a = np.asarray(res.forward(x, t, ctx, pooled, g), np.float32)
        b = np.asarray(strm.forward(x, t, ctx, pooled, g), np.float32)
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-5)

    def test_fp8_trajectory_image_quality_flux(self):
        """END-TO-END fp8 quality pin (r04 VERDICT weak #6: the ~0.1%
        per-matmul bound was never propagated to an image-level metric):
        a full tiny-FLUX sampling trajectory with fp8 weights vs the
        exact trajectory, compared as IMAGES.

        Two ladders isolate the two effects: ``stream_dtype="native"``
        runs the same offload block programs with EXACT weights (the
        restructure itself must be image-identical to numerical noise),
        then fp8 adds only quantization, whose accumulated image error
        is pinned by PSNR."""
        from comfyui_distributed_tpu.diffusion.pipeline_flow import (
            FlowPipeline, FlowSpec)
        from comfyui_distributed_tpu.models.vae import (AutoencoderKL,
                                                        VAEConfig)
        from comfyui_distributed_tpu.parallel import build_mesh

        cfg = DiTConfig.tiny(pos_embed="rope")
        model, params = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                                 context_len=6)
        vae = AutoencoderKL(VAEConfig.tiny(dtype="float32")).init(
            jax.random.key(1), image_hw=(16, 16))
        pipe = FlowPipeline(model, params, vae)
        spec = FlowSpec(height=16, width=16, steps=8)
        ctx = jax.random.normal(jax.random.key(2), (1, 6, cfg.context_dim))
        pooled = jax.random.normal(jax.random.key(3), (1, cfg.pooled_dim))

        exact = np.asarray(pipe.generate(build_mesh({"dp": 1}), spec, 7,
                                         ctx, pooled), np.float32)
        native = np.asarray(pipe.generate_offloaded(
            spec, 7, ctx, pooled, resident_bytes=1 << 40,
            stream_dtype="native"), np.float32)
        fp8 = np.asarray(pipe.generate_offloaded(
            spec, 7, ctx, pooled, resident_bytes=1 << 40,
            stream_dtype="float8_e4m3fn"), np.float32)
        assert exact.shape == native.shape == fp8.shape

        # the block-program restructure alone: image-identical
        np.testing.assert_allclose(native, exact, atol=2e-3)
        # fp8 quantization, accumulated through the whole trajectory +
        # VAE decode, measured at the image level
        mse = float(np.mean((fp8 - exact) ** 2))
        psnr = 10.0 * np.log10(1.0 / max(mse, 1e-12))
        assert psnr > 25.0, f"fp8 trajectory PSNR {psnr:.1f} dB"
        assert float(np.abs(fp8 - exact).max()) < 0.25

    def test_fp8_trajectory_image_quality_wan(self):
        """Same end-to-end pin for the WAN offload path (video frames):
        fp8 expert residency must not visibly corrupt the clip."""
        from comfyui_distributed_tpu.diffusion.pipeline_video import (
            VideoPipeline, VideoSpec)
        from comfyui_distributed_tpu.models.wan import WanConfig, init_wan
        from comfyui_distributed_tpu.models.wan_vae import (WanVAE3D,
                                                            WanVAEConfig)
        from comfyui_distributed_tpu.parallel import build_mesh

        cfg = WanConfig.tiny()
        model, params = init_wan(cfg, jax.random.key(0),
                                 sample_fhw=(3, 8, 8), context_len=6)
        vae = WanVAE3D(WanVAEConfig.tiny()).init(jax.random.key(1),
                                                 frames=5,
                                                 image_hw=(16, 16))
        pipe = VideoPipeline(model, params, vae)
        spec = VideoSpec(frames=5, height=16, width=16, steps=4)
        ctx = jax.random.normal(jax.random.key(2), (1, 6, cfg.text_dim))
        pooled = jnp.zeros((1, 16))

        exact = np.asarray(pipe.generate(build_mesh({"dp": 1}), spec, 9,
                                         ctx, pooled), np.float32)
        fp8 = np.asarray(pipe.generate_offloaded(
            spec, 9, ctx, resident_bytes=1 << 40,
            stream_dtype="float8_e4m3fn"), np.float32)
        assert fp8.shape == exact.shape
        mse = float(np.mean((fp8 - exact) ** 2))
        psnr = 10.0 * np.log10(1.0 / max(mse, 1e-12))
        assert psnr > 25.0, f"fp8 WAN trajectory PSNR {psnr:.1f} dB"

    def test_executor_prefers_flash_attention(self):
        """The offload executor's block programs must request the pallas
        flash kernel regardless of the seq-length gate: with the fp8 set
        resident, XLA attention OOM'd at compile on the chip (r04,
        16.89 GB vs 15.75 HBM)."""
        cfg, model, params, *_ = _stack()
        off = OffloadedFlux(model, params, resident_bytes=1 << 40)
        assert off.cfg.attn_backend == "flash"

    def test_plan_matches_build(self):
        """``plan_offload`` (shapes-only, what bench.py's RAM guard uses)
        must agree with the executor actually built."""
        from comfyui_distributed_tpu.diffusion.offload import plan_offload

        cfg, model, params, *_ = _stack()
        for budget in (0, 1 << 40):
            for sd in ("native", "float8_e4m3fn"):
                plan = plan_offload(params, budget, sd)
                off = OffloadedFlux(model, params, resident_bytes=budget,
                                    stream_dtype=sd)
                assert plan["fully_resident"] == bool(off.stacked)
                assert set(plan["streamed"]) == set(off.streamed)
                assert plan["resident_bytes"] == off.resident_bytes
                if off.streamed:
                    assert plan["streamed_bytes"] == tree_bytes(
                        off.streamed)

    def test_env_knob_and_bad_value(self, monkeypatch):
        from comfyui_distributed_tpu.diffusion.offload import \
            stream_dtype_default

        monkeypatch.delenv("CDT_OFFLOAD_STREAM_DTYPE", raising=False)
        assert stream_dtype_default() == "float8_e4m3fn"
        monkeypatch.setenv("CDT_OFFLOAD_STREAM_DTYPE", "native")
        assert stream_dtype_default() == "native"
        cfg, model, params, *_ = _stack()
        with pytest.raises(ValueError, match="STREAM_DTYPE"):
            OffloadedFlux(model, params, resident_bytes=0,
                          stream_dtype="int4")


class TestQuantCache:
    """r04: CDT_OFFLOAD_CACHE_DIR persists quantized flat blocks —
    quantizing 12B params costs ~5 single-core minutes per process
    start; a warm cache cuts the build to a disk read."""

    def _params(self):
        cfg = DiTConfig.tiny(pos_embed="rope")
        from comfyui_distributed_tpu.diffusion.offload import \
            materialize_host_params
        from comfyui_distributed_tpu.models.dit import DiT
        _, abstract = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                               context_len=6, abstract=True)
        return DiT(cfg), materialize_host_params(abstract, seed=7)

    def _inputs(self, cfg):
        return (jax.random.normal(jax.random.key(1),
                                  (1, 8, 8, cfg.in_channels)),
                jnp.array([0.5]),
                jax.random.normal(jax.random.key(2),
                                  (1, 6, cfg.context_dim)),
                jax.random.normal(jax.random.key(3), (1, cfg.pooled_dim)),
                jnp.array([3.5]))

    def test_cold_build_writes_warm_build_loads(self, tmp_path,
                                                monkeypatch):
        import comfyui_distributed_tpu.diffusion.offload as off_mod

        monkeypatch.setenv("CDT_OFFLOAD_CACHE_DIR", str(tmp_path))
        model, params = self._params()
        off_cold = OffloadedFlux(model, params, resident_bytes=1 << 40,
                                 stream_dtype="float8_e4m3fn")
        # files live in a fingerprint-named subdir: concurrent builds of
        # DIFFERENT checkpoints in one shared dir can't cross-validate
        assert list(tmp_path.glob("*/manifest.json"))
        assert list(tmp_path.glob("*/double_0.*.npy"))

        calls = []
        real = off_mod._flatten_block
        monkeypatch.setattr(off_mod, "_flatten_block",
                            lambda *a, **k: calls.append(1) or real(*a, **k))
        off_warm = OffloadedFlux(model, params, resident_bytes=1 << 40,
                                 stream_dtype="float8_e4m3fn")
        assert not calls, "warm build must not re-quantize"
        x, t, ctx, pooled, g = self._inputs(model.config)
        np.testing.assert_array_equal(
            np.asarray(off_cold.forward(x, t, ctx, pooled, g)),
            np.asarray(off_warm.forward(x, t, ctx, pooled, g)))

    def test_stale_fingerprint_requantizes(self, tmp_path, monkeypatch):
        monkeypatch.setenv("CDT_OFFLOAD_CACHE_DIR", str(tmp_path))
        model, params = self._params()
        OffloadedFlux(model, params, resident_bytes=1 << 40,
                      stream_dtype="float8_e4m3fn")
        # different weights, same shapes → fingerprint must differ and
        # the stale cache must be ignored (correct output, no crash)
        _, params2 = self._params()
        p2 = jax.tree_util.tree_map(lambda a: a * 1.5
                                    if a.ndim >= 2 else a, params2)
        off2 = OffloadedFlux(model, p2, resident_bytes=1 << 40,
                             stream_dtype="float8_e4m3fn")
        x, t, ctx, pooled, g = self._inputs(model.config)
        want = np.asarray(model.apply(p2, x, t, ctx, pooled, g), np.float32)
        got = np.asarray(off2.forward(x, t, ctx, pooled, g), np.float32)
        rel = np.linalg.norm(got - want) / max(np.linalg.norm(want), 1e-9)
        assert rel < 0.05, rel

    def test_corrupt_entry_falls_back(self, tmp_path, monkeypatch):
        monkeypatch.setenv("CDT_OFFLOAD_CACHE_DIR", str(tmp_path))
        model, params = self._params()
        off1 = OffloadedFlux(model, params, resident_bytes=1 << 40,
                             stream_dtype="float8_e4m3fn")
        for p in tmp_path.glob("*/single_1.*.npy"):
            p.write_bytes(b"garbage")
        off2 = OffloadedFlux(model, params, resident_bytes=1 << 40,
                             stream_dtype="float8_e4m3fn")
        x, t, ctx, pooled, g = self._inputs(model.config)
        np.testing.assert_array_equal(
            np.asarray(off1.forward(x, t, ctx, pooled, g)),
            np.asarray(off2.forward(x, t, ctx, pooled, g)))

    def test_garbled_manifest_shapes_never_fatal(self, tmp_path,
                                                 monkeypatch):
        """Valid-JSON-wrong-shape manifests (a list; metas rows that
        aren't 5-tuples) must degrade to re-quantizing, not crash the
        build (the 'never fatal' contract)."""
        monkeypatch.setenv("CDT_OFFLOAD_CACHE_DIR", str(tmp_path))
        model, params = self._params()
        off1 = OffloadedFlux(model, params, resident_bytes=1 << 40,
                             stream_dtype="float8_e4m3fn")
        (manifest,) = tmp_path.glob("*/manifest.json")
        fp = manifest.parent.name
        for garbage in ("[1, 2]",
                        '{"fingerprint": "%s", "metas": {"double": [1]}}'
                        % fp):
            manifest.write_text(garbage)
            off2 = OffloadedFlux(model, params, resident_bytes=1 << 40,
                                 stream_dtype="float8_e4m3fn")
            x, t, ctx, pooled, g = self._inputs(model.config)
            np.testing.assert_array_equal(
                np.asarray(off1.forward(x, t, ctx, pooled, g)),
                np.asarray(off2.forward(x, t, ctx, pooled, g)))

    def test_unwritable_cache_dir_never_fatal(self, tmp_path,
                                              monkeypatch):
        ro = tmp_path / "ro"
        ro.mkdir()
        ro.chmod(0o500)                      # no write permission
        monkeypatch.setenv("CDT_OFFLOAD_CACHE_DIR", str(ro / "cache"))
        model, params = self._params()
        try:
            off = OffloadedFlux(model, params, resident_bytes=1 << 40,
                                stream_dtype="float8_e4m3fn")
            assert off.stacked                # built fine, just uncached
        finally:
            ro.chmod(0o700)

    def test_no_cache_without_env(self, tmp_path, monkeypatch):
        monkeypatch.delenv("CDT_OFFLOAD_CACHE_DIR", raising=False)
        model, params = self._params()
        OffloadedFlux(model, params, resident_bytes=1 << 40,
                      stream_dtype="float8_e4m3fn")
        assert not list(tmp_path.iterdir())


class TestOffloadedWan:
    """r04: the WAN-side executor over the shared block-store substrate
    — how 14B video experts (28 GB bf16) run on one 16 GB chip."""

    def _stack(self):
        from comfyui_distributed_tpu.models.wan import (WanConfig,
                                                        WanModel, init_wan)

        cfg = WanConfig.tiny()
        model, params = init_wan(cfg, jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (1, 4, 8, 8,
                                                  cfg.in_channels))
        t = jnp.array([0.6])
        ctx = jax.random.normal(jax.random.key(2), (1, 5, cfg.text_dim))
        return cfg, model, params, x, t, ctx

    @pytest.mark.parametrize("resident_bytes", [0, 1 << 40])
    def test_matches_monolithic_apply(self, resident_bytes):
        from comfyui_distributed_tpu.diffusion.offload import OffloadedWan

        cfg, model, params, x, t, ctx = self._stack()
        want = np.asarray(model.apply(params, x, t, ctx))
        off = OffloadedWan(model, params, resident_bytes=resident_bytes,
                           stream_dtype="native")
        if resident_bytes:
            assert off.stacked and not off.streamed
        else:
            assert off.streamed and not off.stacked
        got = np.asarray(off.forward(x, t, ctx))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_fp8_close_and_scan_equals_loop(self):
        from comfyui_distributed_tpu.diffusion.offload import (
            OffloadedWan, materialize_host_params)
        from comfyui_distributed_tpu.models.wan import (WanConfig,
                                                        WanModel, init_wan)

        cfg = WanConfig.tiny()
        model, _ = init_wan(cfg, jax.random.key(0))
        abstract = jax.eval_shape(
            lambda: init_wan(cfg, jax.random.key(0))[1])
        params = materialize_host_params(abstract, seed=9)
        x = jax.random.normal(jax.random.key(1), (1, 4, 8, 8,
                                                  cfg.in_channels))
        t = jnp.array([0.6])
        ctx = jax.random.normal(jax.random.key(2), (1, 5, cfg.text_dim))
        want = np.asarray(model.apply(params, x, t, ctx), np.float32)
        res = OffloadedWan(model, params, resident_bytes=1 << 40,
                           stream_dtype="float8_e4m3fn")
        strm = OffloadedWan(model, params, resident_bytes=0,
                            stream_dtype="float8_e4m3fn")
        a = np.asarray(res.forward(x, t, ctx), np.float32)
        b = np.asarray(strm.forward(x, t, ctx), np.float32)
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-5)
        rel = np.linalg.norm(a - want) / max(np.linalg.norm(want), 1e-9)
        assert rel < 0.05, rel

    def test_cfg_denoiser_matches_batched_formula(self):
        from comfyui_distributed_tpu.diffusion.offload import OffloadedWan

        cfg, model, params, x, t, ctx = self._stack()
        off = OffloadedWan(model, params, resident_bytes=1 << 40,
                           stream_dtype="native")
        g = 4.5
        den = off.denoiser(ctx, guidance_scale=g)
        got = np.asarray(den(x, jnp.float32(0.6)))
        # the batched-concat formula of VideoPipeline._denoiser
        x2 = jnp.concatenate([x, x], axis=0)
        ctx2 = jnp.concatenate([ctx, jnp.zeros_like(ctx)], axis=0)
        t2 = jnp.full((2,), 0.6)
        v2 = model.apply(params, x2, t2, ctx2)
        out2 = x2 - 0.6 * v2
        cond, uncond = np.split(np.asarray(out2), 2, axis=0)
        want = uncond + g * (cond - uncond)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_release_frees_device_buffers(self):
        from comfyui_distributed_tpu.diffusion.offload import OffloadedWan

        cfg, model, params, x, t, ctx = self._stack()
        off = OffloadedWan(model, params, resident_bytes=1 << 40,
                           stream_dtype="native")
        assert off.stacked
        off.release()
        assert not off.stacked and not off.resident


class TestFullScalePlans:
    """Abstract-tree placement plans at the REAL published sizes — no
    materialization (`jax.eval_shape`), so these run in seconds and pin
    the single-chip claims numerically."""

    def test_flux_12b_fp8_fully_resident_at_default_budget(self):
        from comfyui_distributed_tpu.diffusion.offload import plan_offload

        cfg = DiTConfig.flux()
        _, abstract = init_dit(cfg, jax.random.key(0),
                               sample_hw=(128, 128), context_len=512,
                               abstract=True, param_dtype=jnp.bfloat16)
        plan = plan_offload(abstract, int(13 * (1 << 30)),
                            "float8_e4m3fn")
        assert plan["fully_resident"], plan["streamed"]
        assert 11e9 < plan["resident_bytes"] < 13 * (1 << 30)

    def test_wan_14b_fp8_mostly_resident_on_one_chip(self):
        """A 14B WAN expert is 28 GB bf16 (~2x one chip's HBM); fp8 it
        is ~14 GB — a 13.5 GB budget holds ≥90% resident with <2.5 GB
        streaming per step. This is the numeric basis of the 'WAN-14B
        on ONE chip' capability (OffloadedWan)."""
        from comfyui_distributed_tpu.diffusion.offload import (
            _WAN_GLUE_KEYS, plan_offload, tree_bytes)
        from comfyui_distributed_tpu.models.wan import WanConfig, init_wan

        cfg = WanConfig.wan_14b()
        _, abstract = init_wan(cfg, jax.random.key(0),
                               sample_fhw=(9, 60, 104), context_len=512,
                               abstract=True, param_dtype=jnp.bfloat16)
        total = tree_bytes(abstract["params"]
                           if "params" in abstract else abstract)
        assert total > 26e9                      # really 14B-scale bf16
        plan = plan_offload(abstract, int(13.5 * (1 << 30)),
                            "float8_e4m3fn", block_prefixes=("block",),
                            glue_keys=_WAN_GLUE_KEYS)
        assert len(plan["order"]) == cfg.num_layers
        frac = plan["resident_bytes"] / (plan["resident_bytes"]
                                         + plan["streamed_bytes"])
        assert frac > 0.90, frac
        assert plan["streamed_bytes"] < 2.5e9, plan["streamed_bytes"]


class TestGenerateOffloadedVideo:
    """r04: VideoPipeline.generate_offloaded — WAN-14B-class video on
    one chip, including the dual-expert HBM swap."""

    def _pipes(self):
        from comfyui_distributed_tpu.models.wan import WanConfig, init_wan
        from comfyui_distributed_tpu.models.vae import (AutoencoderKL,
                                                        VAEConfig)

        cfg = WanConfig.tiny()
        model, hi = init_wan(cfg, jax.random.key(0), sample_fhw=(5, 8, 8),
                             context_len=6)
        _, lo = init_wan(cfg, jax.random.key(99), sample_fhw=(5, 8, 8),
                         context_len=6)
        vae = AutoencoderKL(VAEConfig.tiny(dtype="float32")).init(
            jax.random.key(1), image_hw=(16, 16))
        ctx = jnp.ones((1, 6, cfg.text_dim)) * 0.1
        pooled = jnp.ones((1, 16)) * 0.2
        return model, hi, lo, vae, ctx, pooled

    def test_single_expert_equals_dp_on_one_device(self):
        from comfyui_distributed_tpu.diffusion.pipeline_video import (
            VideoPipeline, VideoSpec)
        from comfyui_distributed_tpu.parallel import build_mesh

        model, hi, lo, vae, ctx, pooled = self._pipes()
        pipe = VideoPipeline(model, hi, vae)
        spec = VideoSpec(frames=5, height=16, width=16, steps=3,
                         shift=1.0)
        want = np.asarray(pipe.generate(build_mesh({"dp": 1}), spec, 4,
                                        ctx, pooled))
        got = np.asarray(pipe.generate_offloaded(
            spec, 4, ctx, stream_dtype="native"))
        np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-4)

    def test_moe_swap_equals_dp_and_evicts_high(self):
        from comfyui_distributed_tpu.diffusion.pipeline_video import (
            VideoPipeline, VideoSpec)
        from comfyui_distributed_tpu.parallel import build_mesh

        model, hi, lo, vae, ctx, pooled = self._pipes()
        pipe = VideoPipeline(model, hi, vae, dit_params_low=lo,
                             expert_boundary=0.875)
        spec = VideoSpec(frames=5, height=16, width=16, steps=8,
                         shift=1.0)
        from comfyui_distributed_tpu.diffusion.schedules import sigmas_flow
        split = pipe._expert_split(sigmas_flow(8, 1.0))
        assert 0 < split < 8          # the swap path actually runs
        want = np.asarray(pipe.generate(build_mesh({"dp": 1}), spec, 7,
                                        ctx, pooled))
        got = np.asarray(pipe.generate_offloaded(
            spec, 7, ctx, stream_dtype="native"))
        np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-4)
        # high expert released + evicted; low stays cached for the next
        # video
        kinds = {k[1] for k in pipe._fn_cache if k[0] == "offload"}
        assert kinds == {"low"}

    @pytest.mark.parametrize("resident_bytes", [0, None])
    def test_i2v_offloaded_equals_dp_on_one_device(self, resident_bytes):
        """0 → streamed python ladder (inp_fn path); None (default
        budget, tiny model fully resident) → the one-jit resident ladder
        with traced y/mask. Both must match dp."""
        from comfyui_distributed_tpu.diffusion.pipeline_video import \
            VideoSpec
        from comfyui_distributed_tpu.models.registry import ModelRegistry
        from comfyui_distributed_tpu.parallel import build_mesh

        bundle = ModelRegistry().get("wan-i2v-tiny")
        pipe = bundle.pipeline
        spec = VideoSpec(frames=5, height=16, width=16, steps=2,
                         shift=1.0)
        ctx, pooled = bundle.text_encoder.encode(["animate"])
        img = jnp.ones((1, 16, 16, 3)) * 0.3
        want = np.asarray(pipe.generate_i2v(build_mesh({"dp": 1}), spec,
                                            6, img, ctx, pooled))
        got = np.asarray(pipe.generate_offloaded_i2v(
            spec, 6, img, ctx, stream_dtype="native",
            resident_bytes=resident_bytes))
        np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-4)

    @pytest.mark.parametrize("resident_bytes", [0, None])
    def test_cfg_offloaded_equals_dp(self, resident_bytes):
        """guidance_scale > 1 exercises the CFG branch of BOTH offload
        ladders (in-trace cond/uncond for resident, sequential python
        for streamed) against the dp batched-CFG path."""
        from comfyui_distributed_tpu.diffusion.pipeline_video import (
            VideoPipeline, VideoSpec)
        from comfyui_distributed_tpu.parallel import build_mesh

        model, hi, lo, vae, ctx, pooled = self._pipes()
        pipe = VideoPipeline(model, hi, vae)
        spec = VideoSpec(frames=5, height=16, width=16, steps=2,
                         shift=1.0, guidance_scale=4.0)
        want = np.asarray(pipe.generate(build_mesh({"dp": 1}), spec, 9,
                                        ctx, pooled))
        got = np.asarray(pipe.generate_offloaded(
            spec, 9, ctx, stream_dtype="native",
            resident_bytes=resident_bytes))
        np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-4)

    def test_non_euler_and_batch_guards(self):
        from comfyui_distributed_tpu.diffusion.pipeline_video import (
            VideoPipeline, VideoSpec)

        model, hi, lo, vae, ctx, pooled = self._pipes()
        pipe = VideoPipeline(model, hi, vae)
        # streamed (per-step) ladder: euler-only
        with pytest.raises(ValueError, match="euler only"):
            pipe.generate_offloaded(
                VideoSpec(frames=5, height=16, width=16,
                          sampler="dpmpp_2m"), 0, ctx, resident_bytes=0)
        with pytest.raises(ValueError, match="batch 1"):
            pipe.generate_offloaded(
                VideoSpec(frames=5, height=16, width=16), 0,
                jnp.zeros((2, 6, model.config.text_dim)))

    def test_resident_video_sampler_equals_dp(self):
        """A non-euler sampler through the resident video ladder matches
        dp — the capability the euler-only python loop lacks."""
        from comfyui_distributed_tpu.diffusion.pipeline_video import (
            VideoPipeline, VideoSpec)
        from comfyui_distributed_tpu.parallel import build_mesh

        model, hi, lo, vae, ctx, pooled = self._pipes()
        pipe = VideoPipeline(model, hi, vae)
        spec = VideoSpec(frames=5, height=16, width=16, steps=3,
                         shift=1.0, sampler="dpmpp_2m")
        want = np.asarray(pipe.generate(build_mesh({"dp": 1}), spec, 13,
                                        ctx, pooled))
        got = np.asarray(pipe.generate_offloaded(
            spec, 13, ctx, stream_dtype="native"))
        np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-4)


class TestInterruptAndLadderMode:
    """r04: offloaded sampling honors /distributed/interrupt between
    steps (CDT_OFFLOAD_LADDER=step keeps fully-resident runs on the
    interruptible per-step loop; 'jit' — the default — trades that for
    a single compiled ladder)."""

    def test_should_stop_raises_between_steps(self):
        from comfyui_distributed_tpu.diffusion import sigmas_flow

        calls = []

        def den(x, s):
            calls.append(1)
            return x * 0.5

        x = jnp.ones((1, 4, 4, 2))
        with pytest.raises(InterruptedError, match="interrupted at step"):
            sample_euler_py(den, x, sigmas_flow(6, 1.0),
                            should_stop=lambda: len(calls) >= 2)
        assert len(calls) == 2          # stopped before the third step

    def test_ladder_mode_env(self, monkeypatch):
        from comfyui_distributed_tpu.diffusion.offload import ladder_mode

        monkeypatch.delenv("CDT_OFFLOAD_LADDER", raising=False)
        assert ladder_mode() == "jit"
        monkeypatch.setenv("CDT_OFFLOAD_LADDER", "step")
        assert ladder_mode() == "step"
        monkeypatch.setenv("CDT_OFFLOAD_LADDER", "bogus")
        with pytest.raises(ValueError, match="LADDER"):
            ladder_mode()

    def test_step_mode_resident_still_equals_dp(self, monkeypatch):
        """CDT_OFFLOAD_LADDER=step on a fully-resident executor runs the
        python loop over the fused forward — same numbers as dp."""
        from comfyui_distributed_tpu.diffusion.pipeline_flow import (
            FlowPipeline, FlowSpec)
        from comfyui_distributed_tpu.models.vae import (AutoencoderKL,
                                                        VAEConfig)
        from comfyui_distributed_tpu.parallel import build_mesh

        monkeypatch.setenv("CDT_OFFLOAD_LADDER", "step")
        cfg = DiTConfig.tiny(pos_embed="rope")
        model, params = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                                 context_len=6)
        vae = AutoencoderKL(VAEConfig.tiny(dtype="float32")).init(
            jax.random.key(1), image_hw=(16, 16))
        pipe = FlowPipeline(model, params, vae)
        ctx = jnp.ones((1, 6, cfg.context_dim)) * 0.1
        pooled = jnp.ones((1, cfg.pooled_dim)) * 0.2
        spec = FlowSpec(height=16, width=16, steps=3)
        want = np.asarray(pipe.generate(build_mesh({"dp": 1}), spec, 5,
                                        ctx, pooled))
        got = np.asarray(pipe.generate_offloaded(
            spec, 5, ctx, pooled, resident_bytes=1 << 40,
            stream_dtype="native"))
        np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-4)

    def test_node_interrupt_mid_offload(self, tmp_config, monkeypatch):
        """A set interrupt_event + step-mode ladder aborts the offloaded
        node with InterruptedError (the executor surfaces it like its
        own between-node check)."""
        import threading

        from comfyui_distributed_tpu.graph.node import get_node
        from comfyui_distributed_tpu.models.registry import (PRESETS,
                                                             ModelBundle)

        monkeypatch.setenv("CDT_OFFLOAD_LADDER", "step")
        monkeypatch.delenv("CDT_OFFLOAD", raising=False)
        ev = threading.Event()
        ev.set()
        bundle = ModelBundle(PRESETS["flux-tiny"])
        ctx, pooled = bundle.text_encoder.encode(["stop me"])
        with pytest.raises(InterruptedError):
            get_node("TPUFlowTxt2Img")().execute(
                bundle, {"context": ctx, "pooled": pooled},
                seed=1, steps=3, width=16, height=16, mode="offload",
                interrupt_event=ev)


class TestEulerLadder:
    def test_matches_scan_sampler(self):
        from comfyui_distributed_tpu.diffusion import sample, sigmas_flow

        sigmas = sigmas_flow(6, shift=1.0)
        x = jax.random.normal(jax.random.key(0), (1, 4, 4, 2))
        den = lambda xx, s: xx * 0.6
        want = np.asarray(sample("euler", den, x, sigmas))
        got = np.asarray(sample_euler_py(den, x, sigmas))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class TestGenerateOffloaded:
    @pytest.mark.parametrize("resident_bytes", [0, 1 << 40])
    def test_equals_dp_generate_on_one_device(self, resident_bytes):
        """resident_bytes=0 → streamed python ladder; huge → the
        fully-resident ONE-JIT ladder (sample_euler_resident). Both must
        equal the dp path on one device."""
        from comfyui_distributed_tpu.diffusion.pipeline_flow import (
            FlowPipeline, FlowSpec)
        from comfyui_distributed_tpu.models.vae import (AutoencoderKL,
                                                        VAEConfig)
        from comfyui_distributed_tpu.parallel import build_mesh

        cfg = DiTConfig.tiny(pos_embed="rope")
        model, params = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                                 context_len=6)
        vae = AutoencoderKL(VAEConfig.tiny(dtype="float32")).init(
            jax.random.key(1), image_hw=(16, 16))
        pipe = FlowPipeline(model, params, vae)
        ctx = jnp.ones((1, 6, cfg.context_dim)) * 0.1
        pooled = jnp.ones((1, cfg.pooled_dim)) * 0.2
        spec = FlowSpec(height=16, width=16, steps=3)
        want = np.asarray(pipe.generate(build_mesh({"dp": 1}), spec, 5,
                                        ctx, pooled))
        off = pipe.offload_executor(resident_bytes=resident_bytes,
                                    stream_dtype="native")
        assert bool(off.stacked) == bool(resident_bytes)
        got = np.asarray(pipe.generate_offloaded(
            spec, 5, ctx, pooled, resident_bytes=resident_bytes,
            stream_dtype="native"))
        np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-4)

    def test_non_euler_streamed_raises_resident_works(self):
        """The per-step python ladder is euler-only; the fully-resident
        in-trace ladder runs EVERY registered sampler."""
        from comfyui_distributed_tpu.diffusion.pipeline_flow import (
            FlowPipeline, FlowSpec)
        from comfyui_distributed_tpu.models.vae import (AutoencoderKL,
                                                        VAEConfig)

        cfg = DiTConfig.tiny()
        model, params = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                                 context_len=6)
        vae = AutoencoderKL(VAEConfig.tiny()).init(jax.random.key(1),
                                                   image_hw=(16, 16))
        pipe = FlowPipeline(model, params, vae)
        ctx = jnp.zeros((1, 6, cfg.context_dim))
        pooled = jnp.zeros((1, cfg.pooled_dim))
        spec = FlowSpec(height=16, width=16, steps=2, sampler="heun")
        with pytest.raises(ValueError, match="euler only"):
            pipe.generate_offloaded(spec, 0, ctx, pooled,
                                    resident_bytes=0)
        out = pipe.generate_offloaded(spec, 0, ctx, pooled,
                                      resident_bytes=1 << 40)
        assert np.asarray(out).shape == (1, 16, 16, 3)

    @pytest.mark.parametrize("sampler", ["dpmpp_2m", "euler_ancestral"])
    def test_resident_ladder_samplers_equal_dp(self, sampler):
        """Non-euler samplers through the resident jit ladder must match
        the dp path — including ancestral ones (the ladder threads the
        SAME fold_in(key, 0) the dp shard-0 uses for its noise draws)."""
        from comfyui_distributed_tpu.diffusion.pipeline_flow import (
            FlowPipeline, FlowSpec)
        from comfyui_distributed_tpu.models.vae import (AutoencoderKL,
                                                        VAEConfig)
        from comfyui_distributed_tpu.parallel import build_mesh

        cfg = DiTConfig.tiny(pos_embed="rope")
        model, params = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                                 context_len=6)
        vae = AutoencoderKL(VAEConfig.tiny(dtype="float32")).init(
            jax.random.key(1), image_hw=(16, 16))
        pipe = FlowPipeline(model, params, vae)
        ctx = jnp.ones((1, 6, cfg.context_dim)) * 0.1
        pooled = jnp.ones((1, cfg.pooled_dim)) * 0.2
        spec = FlowSpec(height=16, width=16, steps=3, sampler=sampler)
        want = np.asarray(pipe.generate(build_mesh({"dp": 1}), spec, 11,
                                        ctx, pooled))
        got = np.asarray(pipe.generate_offloaded(
            spec, 11, ctx, pooled, resident_bytes=1 << 40,
            stream_dtype="native"))
        np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-4)


class TestPlumbing:
    def test_materialize_host_params_shapes(self):
        cfg = DiTConfig.tiny()
        _, abstract = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                               context_len=6, abstract=True)
        host = materialize_host_params(abstract, seed=1)
        a_leaves = jax.tree_util.tree_leaves(abstract)
        h_leaves = jax.tree_util.tree_leaves(host)
        assert all(h.shape == a.shape and h.dtype == a.dtype
                   for h, a in zip(h_leaves, a_leaves))
        assert all(isinstance(h, np.ndarray) for h in h_leaves)

    def test_knobs(self, monkeypatch):
        monkeypatch.delenv("CDT_OFFLOAD", raising=False)
        assert not offload_enabled()
        monkeypatch.setenv("CDT_OFFLOAD", "1")
        assert offload_enabled()
        monkeypatch.setenv("CDT_OFFLOAD_RESIDENT_GB", "2.5")
        assert resident_budget_bytes() == int(2.5 * (1 << 30))


class TestNodeAndCaching:
    def test_executor_cached_across_calls(self):
        """generate_offloaded must reuse the streamed executor (resident
        upload + 4 compiled programs) — rebuilding per image costs
        minutes at FLUX scale."""
        from comfyui_distributed_tpu.diffusion.pipeline_flow import (
            FlowPipeline, FlowSpec)
        from comfyui_distributed_tpu.models.vae import (AutoencoderKL,
                                                        VAEConfig)

        cfg = DiTConfig.tiny(pos_embed="rope")
        model, params = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                                 context_len=6)
        vae = AutoencoderKL(VAEConfig.tiny()).init(jax.random.key(1),
                                                   image_hw=(16, 16))
        pipe = FlowPipeline(model, params, vae)
        ctx = jnp.zeros((1, 6, cfg.context_dim))
        pooled = jnp.zeros((1, cfg.pooled_dim))
        spec = FlowSpec(height=16, width=16, steps=2)
        pipe.generate_offloaded(spec, 0, ctx, pooled, resident_bytes=0)
        first = pipe.offload_executor(resident_bytes=0)
        assert len(pipe._fn_cache) == 1
        pipe.generate_offloaded(spec, 1, ctx, pooled, resident_bytes=0)
        assert pipe.offload_executor(resident_bytes=0) is first
        assert len(pipe._fn_cache) == 1

    def test_batch_gt_one_raises(self):
        from comfyui_distributed_tpu.diffusion.pipeline_flow import (
            FlowPipeline, FlowSpec)
        from comfyui_distributed_tpu.models.vae import (AutoencoderKL,
                                                        VAEConfig)

        cfg = DiTConfig.tiny()
        model, params = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                                 context_len=6)
        vae = AutoencoderKL(VAEConfig.tiny()).init(jax.random.key(1),
                                                   image_hw=(16, 16))
        pipe = FlowPipeline(model, params, vae)
        with pytest.raises(ValueError, match="batch 1"):
            pipe.generate_offloaded(
                FlowSpec(height=16, width=16, per_device_batch=2), 0,
                jnp.zeros((1, 6, cfg.context_dim)),
                jnp.zeros((1, cfg.pooled_dim)))

    def test_offload_mode_reports_progress(self, tmp_config, monkeypatch):
        """The offloaded python ladder must feed the SAME per-step
        progress machinery the compiled samplers drive (VERDICT-style
        parity: t2v/flux offload jobs are the longest-running work —
        0/N-until-done progress is a regression)."""
        from comfyui_distributed_tpu.cluster.progress import \
            ProgressTracker
        from comfyui_distributed_tpu.graph.node import get_node
        from comfyui_distributed_tpu.models.registry import (PRESETS,
                                                             ModelBundle)

        monkeypatch.delenv("CDT_OFFLOAD", raising=False)
        tracker = ProgressTracker()
        bundle = ModelBundle(PRESETS["flux-tiny"])
        ctx, pooled = bundle.text_encoder.encode(["progress"])
        (img,) = get_node("TPUFlowTxt2Img")().execute(
            bundle, {"context": ctx, "pooled": pooled},
            seed=1, steps=3, width=16, height=16, mode="offload",
            prompt_id="pp1", progress_tracker=tracker)
        snap = tracker.snapshot("pp1")
        assert snap is not None and snap["done"] and not snap["failed"]
        assert snap["step"] == 3
        assert tracker.preview_png("pp1") is not None

    def test_video_node_offload_mode(self, tmp_config, monkeypatch):
        """mode='offload' routes TPUTxt2Video through OffloadedWan."""
        from comfyui_distributed_tpu.graph.node import get_node
        from comfyui_distributed_tpu.models.registry import ModelRegistry

        monkeypatch.delenv("CDT_OFFLOAD", raising=False)
        bundle = ModelRegistry().get("wan-tiny-3d")
        ctx, pooled = bundle.text_encoder.encode(["offload clip"])
        (images,) = get_node("TPUTxt2Video")().execute(
            bundle, {"context": ctx, "pooled": pooled},
            seed=3, frames=5, steps=1, width=16, height=16,
            mode="offload")
        assert np.asarray(images).shape == (5, 16, 16, 3)

    def test_node_offload_mode(self, tmp_config, monkeypatch):
        """mode='offload' (or CDT_OFFLOAD=1 with dp) routes the flow node
        through the streamed executor."""
        from comfyui_distributed_tpu.graph.node import get_node
        from comfyui_distributed_tpu.models.registry import (PRESETS,
                                                             ModelBundle)

        monkeypatch.delenv("CDT_OFFLOAD", raising=False)
        bundle = ModelBundle(PRESETS["flux-tiny"])
        node = get_node("TPUFlowTxt2Img")()
        ctx, pooled = bundle.text_encoder.encode(["offload"])
        (img,) = node.execute(bundle, {"context": ctx, "pooled": pooled},
                              seed=1, steps=2, width=16, height=16,
                              mode="offload")
        assert np.asarray(img).shape == (1, 16, 16, 3)
