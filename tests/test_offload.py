"""Host-offloaded FLUX execution (diffusion/offload.py): block streaming
must be numerically invisible — the offloaded forward equals DiT.apply,
the python euler ladder equals the scan sampler, and the end-to-end
offloaded generate equals the dp pipeline on one device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.diffusion.offload import (
    OffloadedFlux,
    materialize_host_params,
    offload_enabled,
    resident_budget_bytes,
    sample_euler_py,
    tree_bytes,
)
from comfyui_distributed_tpu.models.dit import DiTConfig, init_dit

pytestmark = pytest.mark.slow  # compile-heavy: builds/jits real model stacks


def _stack(pos_embed="rope"):
    cfg = DiTConfig.tiny(pos_embed=pos_embed)
    model, params = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                             context_len=6)
    x = jax.random.normal(jax.random.key(1), (2, 8, 8, cfg.in_channels))
    t = jnp.array([0.7, 0.3])
    ctx = jax.random.normal(jax.random.key(2), (2, 6, cfg.context_dim))
    pooled = jax.random.normal(jax.random.key(3), (2, cfg.pooled_dim))
    return cfg, model, params, x, t, ctx, pooled


class TestFlatBlocks:
    """r04: streamed blocks are flattened to one contiguous buffer per
    dtype (one device_put per block instead of ~20 — per-leaf RTT
    dominated the tunneled stream). The layout must round-trip exactly."""

    def test_roundtrip_uniform_dtype(self):
        from comfyui_distributed_tpu.diffusion.offload import (
            _flatten_block, _unflatten_block)

        blk = {"attn": {"kernel": np.arange(12, dtype=np.float32)
                        .reshape(3, 4),
                        "bias": np.ones(4, np.float32)},
               "norm": {"scale": np.full((3,), 2.0, np.float32)}}
        bufs, treedef, metas = _flatten_block(blk)
        assert set(bufs) == {"float32"}
        assert bufs["float32"].shape == (12 + 4 + 3,)
        out = jax.tree_util.tree_map(
            np.asarray, _unflatten_block(
                {k: jnp.asarray(v) for k, v in bufs.items()},
                treedef, metas))
        jax.tree_util.tree_map(np.testing.assert_array_equal, blk, out)

    def test_roundtrip_mixed_dtypes_and_scalars(self):
        from comfyui_distributed_tpu.diffusion.offload import (
            _flatten_block, _unflatten_block)

        blk = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
               "h": jnp.arange(4, dtype=jnp.bfloat16).reshape(2, 2),
               "step": np.int32(7)}                 # scalar leaf
        bufs, treedef, metas = _flatten_block(blk)
        assert set(bufs) == {"float32", "bfloat16", "int32"}
        out = _unflatten_block(
            {k: jnp.asarray(v) for k, v in bufs.items()}, treedef, metas)
        np.testing.assert_array_equal(np.asarray(out["w"]), blk["w"])
        np.testing.assert_array_equal(np.asarray(out["h"]),
                                      np.asarray(blk["h"]))
        assert np.asarray(out["step"]).item() == 7
        assert np.asarray(out["step"]).shape == ()

    def test_unflatten_traces_inside_jit(self):
        """The block programs unflatten in-trace — static offsets must
        trace cleanly and produce the same numbers under jit."""
        from comfyui_distributed_tpu.diffusion.offload import (
            _flatten_block, _unflatten_block)

        blk = {"a": np.random.randn(4, 5).astype(np.float32),
               "b": np.random.randn(5).astype(np.float32)}
        bufs, treedef, metas = _flatten_block(blk)

        @jax.jit
        def apply(bufs, x):
            p = _unflatten_block(bufs, treedef, metas)
            return x @ p["a"] + p["b"]

        x = np.random.randn(2, 4).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(apply(bufs, x)), x @ blk["a"] + blk["b"],
            rtol=1e-6)


class TestForwardEquivalence:
    @pytest.mark.parametrize("pos_embed", ["rope", "sincos"])
    @pytest.mark.parametrize("resident_bytes", [0, 1 << 40])
    def test_matches_monolithic_apply(self, pos_embed, resident_bytes):
        """All-streamed (0) and all-resident (huge) partitions both equal
        the single-program DiT forward."""
        cfg, model, params, x, t, ctx, pooled = _stack(pos_embed)
        g = jnp.array([3.5, 3.5]) if cfg.guidance_embed else None
        want = np.asarray(model.apply(params, x, t, ctx, pooled, g))
        off = OffloadedFlux(model, params, resident_bytes=resident_bytes)
        got = np.asarray(off.forward(x, t, ctx, pooled, g))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_partial_residency_matches(self):
        """A budget that fits only SOME blocks: prefix resident, suffix
        streamed, same numbers."""
        cfg, model, params, x, t, ctx, pooled = _stack()
        inner = params["params"]
        one_block = tree_bytes(inner["double_0"])
        glue = tree_bytes({k: v for k, v in inner.items()
                           if not k.startswith(("double_", "single_"))})
        off = OffloadedFlux(model, params,
                            resident_bytes=glue + one_block * 2 + 64)
        assert 0 < len(off.resident) < len(off.block_order)
        assert set(off.resident) | set(off.streamed) == set(off.block_order)
        g = jnp.array([3.5, 3.5])
        want = np.asarray(model.apply(params, x, t, ctx, pooled, g))
        np.testing.assert_allclose(
            np.asarray(off.forward(x, t, ctx, pooled, g)), want,
            rtol=2e-5, atol=2e-5)

    def test_host_numpy_params_accepted(self):
        """The real offload scenario: params arrive as host numpy (a
        full-size init can't live on device)."""
        cfg, model, params, x, t, ctx, pooled = _stack()
        host = jax.tree_util.tree_map(np.asarray, params)
        off = OffloadedFlux(model, host, resident_bytes=0)
        g = jnp.array([3.5, 3.5])
        want = np.asarray(model.apply(params, x, t, ctx, pooled, g))
        np.testing.assert_allclose(
            np.asarray(off.forward(x, t, ctx, pooled, g)), want,
            rtol=2e-5, atol=2e-5)


class TestEulerLadder:
    def test_matches_scan_sampler(self):
        from comfyui_distributed_tpu.diffusion import sample, sigmas_flow

        sigmas = sigmas_flow(6, shift=1.0)
        x = jax.random.normal(jax.random.key(0), (1, 4, 4, 2))
        den = lambda xx, s: xx * 0.6
        want = np.asarray(sample("euler", den, x, sigmas))
        got = np.asarray(sample_euler_py(den, x, sigmas))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class TestGenerateOffloaded:
    def test_equals_dp_generate_on_one_device(self):
        from comfyui_distributed_tpu.diffusion.pipeline_flow import (
            FlowPipeline, FlowSpec)
        from comfyui_distributed_tpu.models.vae import (AutoencoderKL,
                                                        VAEConfig)
        from comfyui_distributed_tpu.parallel import build_mesh

        cfg = DiTConfig.tiny(pos_embed="rope")
        model, params = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                                 context_len=6)
        vae = AutoencoderKL(VAEConfig.tiny(dtype="float32")).init(
            jax.random.key(1), image_hw=(16, 16))
        pipe = FlowPipeline(model, params, vae)
        ctx = jnp.ones((1, 6, cfg.context_dim)) * 0.1
        pooled = jnp.ones((1, cfg.pooled_dim)) * 0.2
        spec = FlowSpec(height=16, width=16, steps=3)
        want = np.asarray(pipe.generate(build_mesh({"dp": 1}), spec, 5,
                                        ctx, pooled))
        got = np.asarray(pipe.generate_offloaded(spec, 5, ctx, pooled,
                                                 resident_bytes=0))
        np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-4)

    def test_non_euler_raises(self):
        from comfyui_distributed_tpu.diffusion.pipeline_flow import (
            FlowPipeline, FlowSpec)
        from comfyui_distributed_tpu.models.vae import (AutoencoderKL,
                                                        VAEConfig)

        cfg = DiTConfig.tiny()
        model, params = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                                 context_len=6)
        vae = AutoencoderKL(VAEConfig.tiny()).init(jax.random.key(1),
                                                   image_hw=(16, 16))
        pipe = FlowPipeline(model, params, vae)
        with pytest.raises(ValueError, match="euler"):
            pipe.generate_offloaded(
                FlowSpec(height=16, width=16, sampler="heun"), 0,
                jnp.zeros((1, 6, cfg.context_dim)),
                jnp.zeros((1, cfg.pooled_dim)))


class TestPlumbing:
    def test_materialize_host_params_shapes(self):
        cfg = DiTConfig.tiny()
        _, abstract = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                               context_len=6, abstract=True)
        host = materialize_host_params(abstract, seed=1)
        a_leaves = jax.tree_util.tree_leaves(abstract)
        h_leaves = jax.tree_util.tree_leaves(host)
        assert all(h.shape == a.shape and h.dtype == a.dtype
                   for h, a in zip(h_leaves, a_leaves))
        assert all(isinstance(h, np.ndarray) for h in h_leaves)

    def test_knobs(self, monkeypatch):
        monkeypatch.delenv("CDT_OFFLOAD", raising=False)
        assert not offload_enabled()
        monkeypatch.setenv("CDT_OFFLOAD", "1")
        assert offload_enabled()
        monkeypatch.setenv("CDT_OFFLOAD_RESIDENT_GB", "2.5")
        assert resident_budget_bytes() == int(2.5 * (1 << 30))


class TestNodeAndCaching:
    def test_executor_cached_across_calls(self):
        """generate_offloaded must reuse the streamed executor (resident
        upload + 4 compiled programs) — rebuilding per image costs
        minutes at FLUX scale."""
        from comfyui_distributed_tpu.diffusion.pipeline_flow import (
            FlowPipeline, FlowSpec)
        from comfyui_distributed_tpu.models.vae import (AutoencoderKL,
                                                        VAEConfig)

        cfg = DiTConfig.tiny(pos_embed="rope")
        model, params = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                                 context_len=6)
        vae = AutoencoderKL(VAEConfig.tiny()).init(jax.random.key(1),
                                                   image_hw=(16, 16))
        pipe = FlowPipeline(model, params, vae)
        ctx = jnp.zeros((1, 6, cfg.context_dim))
        pooled = jnp.zeros((1, cfg.pooled_dim))
        spec = FlowSpec(height=16, width=16, steps=2)
        pipe.generate_offloaded(spec, 0, ctx, pooled, resident_bytes=0)
        key = ("offload", 0, id(pipe.dit_params))
        first = pipe._fn_cache[key]
        pipe.generate_offloaded(spec, 1, ctx, pooled, resident_bytes=0)
        assert pipe._fn_cache[key] is first

    def test_batch_gt_one_raises(self):
        from comfyui_distributed_tpu.diffusion.pipeline_flow import (
            FlowPipeline, FlowSpec)
        from comfyui_distributed_tpu.models.vae import (AutoencoderKL,
                                                        VAEConfig)

        cfg = DiTConfig.tiny()
        model, params = init_dit(cfg, jax.random.key(0), sample_hw=(8, 8),
                                 context_len=6)
        vae = AutoencoderKL(VAEConfig.tiny()).init(jax.random.key(1),
                                                   image_hw=(16, 16))
        pipe = FlowPipeline(model, params, vae)
        with pytest.raises(ValueError, match="batch 1"):
            pipe.generate_offloaded(
                FlowSpec(height=16, width=16, per_device_batch=2), 0,
                jnp.zeros((1, 6, cfg.context_dim)),
                jnp.zeros((1, cfg.pooled_dim)))

    def test_node_offload_mode(self, tmp_config, monkeypatch):
        """mode='offload' (or CDT_OFFLOAD=1 with dp) routes the flow node
        through the streamed executor."""
        from comfyui_distributed_tpu.graph.node import get_node
        from comfyui_distributed_tpu.models.registry import (PRESETS,
                                                             ModelBundle)

        monkeypatch.delenv("CDT_OFFLOAD", raising=False)
        bundle = ModelBundle(PRESETS["flux-tiny"])
        node = get_node("TPUFlowTxt2Img")()
        ctx, pooled = bundle.text_encoder.encode(["offload"])
        (img,) = node.execute(bundle, {"context": ctx, "pooled": pooled},
                              seed=1, steps=2, width=16, height=16,
                              mode="offload")
        assert np.asarray(img).shape == (1, 16, 16, 3)
